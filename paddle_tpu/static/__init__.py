"""paddle.static analog — deferred-execution graph API over the eager tape.

Reference: python/paddle/static/ (Program/Executor/data, SURVEY.md §2.6) where
a Program is a protobuf op graph executed by the C++ PirInterpreter.

TPU-native redesign: there is no separate graph IR — the eager tape (core/
tensor.py Node DAG, each node carrying a pure `fwd_fn`) IS the captured
program. `static.data` creates named placeholder tensors; building ops under
`program_guard` records the tape; `Executor.run(prog, feed, fetch_list)`
REPLAYS the tape DAG with feed values substituted at the placeholders,
compiled once per (feed shapes, fetches) signature with jax.jit — the analog
of PirInterpreter's first-run lowering + cached instruction list. Training
loops belong to the dygraph/jit path (TrainStep); the static surface covers
graph capture, feed/fetch execution, and save/load_inference_model.
"""
from __future__ import annotations

import contextlib
import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes

__all__ = [
    "Program", "program_guard", "default_main_program", "default_startup_program",
    "data", "InputSpec", "Executor", "save_inference_model",
    "load_inference_model", "name_scope", "nn",
]


class Program:
    """Captured-graph container: tracks placeholders + fetch targets created
    in its guard scope (reference: base/framework.py Program:5890)."""

    def __init__(self):
        self.placeholders = {}
        self.random_seed = None
        self._tensors = []

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def __repr__(self):
        return (f"Program(placeholders={list(self.placeholders)}, "
                f"tensors={len(self._tensors)})")


_default_main = Program()
_default_startup = Program()
_prog_stack = [_default_main]


def default_main_program():
    return _prog_stack[-1]


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _prog_stack.append(main_program)
    try:
        yield
    finally:
        _prog_stack.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    with jax.named_scope(prefix or "scope"):
        yield


class InputSpec:
    """Shape/dtype spec (reference: static/input.py InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, str(tensor.dtype), name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference: static/input.py data). Returns a zero
    Tensor tagged with the feed name; -1 dims become 1 at trace time and are
    re-specialized per feed shape at Executor.run."""
    shp = [1 if (d is None or d < 0) else int(d) for d in shape]
    t = Tensor(jnp.zeros(shp, dtypes.convert_dtype(dtype)), stop_gradient=False)
    t.name = name
    t._feed_name = name
    default_main_program().placeholders[name] = t
    return t


def _replay(fetch_leaf_tensors, feed_values):
    """Recompute fetch values by walking the tape DAG, substituting feeds.

    feed_values: {feed_name: jax value}. Pure: usable under jax.jit.
    """
    node_memo = {}

    def tensor_value(t):
        fname = getattr(t, "_feed_name", None)
        if fname is not None and fname in feed_values:
            return feed_values[fname]
        node = t._node
        if node is None:
            return t._value
        leaves = node_leaves(node)
        return leaves[t._out_index]

    def node_leaves(node):
        got = node_memo.get(id(node))
        if got is not None:
            return got
        ins = [tensor_value(p) for p in node.parents]
        out = node.fwd_fn(*ins)
        leaves = jax.tree_util.tree_flatten(out)[0]
        node_memo[id(node)] = leaves
        return leaves

    return [tensor_value(t) for t in fetch_leaf_tensors]


class Executor:
    """Feed/fetch executor over captured graphs (reference: base/executor.py
    Executor:1237 -> StandaloneExecutor). jit-compiles the replay per
    (fetches, feed signature) and caches the executable."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, return_numpy=True):
        feed = feed or {}
        fetch_list = fetch_list or []
        fetches = [f for f in fetch_list]
        for f in fetches:
            if not isinstance(f, Tensor):
                raise TypeError(f"fetch_list entries must be Tensors, got {f!r}")
        feed_vals = {k: jnp.asarray(v._value if isinstance(v, Tensor) else v)
                     for k, v in feed.items()}
        key = (tuple(id(f) for f in fetches),
               tuple(sorted((k, v.shape, str(v.dtype))
                            for k, v in feed_vals.items())))
        fn = self._cache.get(key)
        if fn is None:
            names = sorted(feed_vals)

            def run_fn(*vals):
                return _replay(fetches, dict(zip(names, vals)))
            fn = jax.jit(run_fn)
            self._cache[key] = (fn, names)
        fn, names = self._cache[key]
        outs = fn(*[feed_vals[n] for n in names])
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o, stop_gradient=True) for o in outs]

    def close(self):
        self._cache.clear()


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Serialize a captured graph (reference: static/io.py save_inference_model).

    TPU-native: stores the REPLAY CLOSURE's jaxpr-equivalent by re-tracing the
    fetches as a function of the feeds, plus all captured constants, with
    pickle of the jitted function's inputs — practically: we store feed specs
    and the fetch values' computation via jax.export when available, else the
    feed/fetch tensors for same-process reuse."""
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    fetch_vars = (fetch_vars if isinstance(fetch_vars, (list, tuple))
                  else [fetch_vars])
    names = [getattr(v, "_feed_name", getattr(v, "name", None))
             for v in feed_vars]

    def fn(*vals):
        return _replay(fetch_vars, dict(zip(names, vals)))

    args = [jnp.zeros(v.shape, v._value.dtype) for v in feed_vars]
    payload = {"feed_names": names,
               "feed_specs": [(v.shape, str(np.dtype(v.dtype))) for v in feed_vars],
               "fetch_names": [getattr(v, "name", None) or f"fetch_{i}"
                               for i, v in enumerate(fetch_vars)]}
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    try:
        from jax import export as jax_export
        exported = jax_export.export(jax.jit(fn))(
            *[jax.ShapeDtypeStruct(a.shape, a.dtype) for a in args])
        payload["serialized"] = exported.serialize()
        payload["format"] = "jax_export"
    except Exception:
        outs = fn(*args)
        payload["format"] = "none"
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump(payload, f)
    return path_prefix + ".pdmodel"


def load_inference_model(path_prefix, executor=None, _return_meta=False,
                         **kwargs):
    """Load a saved inference graph; returns (program, feed_names, fetch_fn),
    or (fetch_fn, payload_meta) when _return_meta=True (paddle.inference path)."""
    path = path_prefix
    if not path.endswith(".pdmodel"):
        path = path_prefix + ".pdmodel"
    with open(path, "rb") as f:
        payload = pickle.load(f)
    names = payload["feed_names"]
    if payload.get("format") == "jax_export":
        from jax import export as jax_export
        exported = jax_export.deserialize(payload["serialized"])

        def fetch_fn(*vals):
            return exported.call(*[jnp.asarray(v) for v in vals])

        if _return_meta:
            return fetch_fn, payload
        return Program(), names, fetch_fn
    raise RuntimeError("model was saved without jax.export support")


class nn:
    """paddle.static.nn parity namespace: static layers are the same layers
    (the program tape records whatever ops they dispatch)."""

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from ..nn.layer.common import Linear
        from ..nn import functional as F
        from .. import ops
        # paddle semantics: flatten dims [num_flatten_dims:] into the
        # projected axis (base/layers fc)
        if num_flatten_dims != len(x.shape) - 1:
            x = ops.flatten(x, start_axis=num_flatten_dims)
        lin = Linear(x.shape[-1], size)
        out = lin(x)
        if activation:
            out = getattr(F, activation)(out)
        return out

    @staticmethod
    def conv2d(input, num_filters, filter_size, stride=1, padding=0,
               dilation=1, groups=1, act=None, name=None, **kwargs):
        from ..nn.layer.conv import Conv2D
        from ..nn import functional as F
        conv = Conv2D(input.shape[1], num_filters, filter_size, stride,
                      padding, dilation, groups)
        out = conv(input)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
                   data_layout="NCHW", name=None, **kwargs):
        from ..nn.layer.norm import BatchNorm2D
        from ..nn import functional as F
        ch_axis = 1 if data_layout == "NCHW" else -1
        bn = BatchNorm2D(input.shape[ch_axis], momentum=momentum,
                         epsilon=epsilon, data_format=data_layout)
        if is_test:
            bn.eval()
        out = bn(input)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def embedding(input, size, is_sparse=False, is_distributed=False,
                  padding_idx=None, name=None, **kwargs):
        from ..nn.layer.common import Embedding
        return Embedding(size[0], size[1], padding_idx=padding_idx)(input)

    @staticmethod
    def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
                   epsilon=1e-5, act=None, name=None, **kwargs):
        from ..nn import functional as F
        shape = input.shape[begin_norm_axis:]
        # affine-less LN equals ones/zeros affine — skip the constant tensors
        out = F.layer_norm(input, shape, weight=None, bias=None,
                           epsilon=epsilon)
        if act:
            out = getattr(F, act)(out)
        return out

    @staticmethod
    def dropout(x, dropout_prob=0.5, is_test=False, name=None, **kwargs):
        from ..nn import functional as F
        return F.dropout(x, p=dropout_prob, training=not is_test)
