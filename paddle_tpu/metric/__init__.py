"""paddle.metric analog (reference: python/paddle/metric/metrics.py —
Metric base, Accuracy, Precision, Recall, Auc).

Metrics accumulate on host in numpy (cheap scalar state); inputs may be
paddle_tpu Tensors or arrays."""
from __future__ import annotations

import abc

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    if isinstance(x, Tensor):
        return np.asarray(x._value)
    return np.asarray(x)


class Metric(abc.ABC):
    """Reference: metric/metrics.py Metric — reset/update/accumulate/name."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        ...

    @abc.abstractmethod
    def update(self, *args):
        ...

    @abc.abstractmethod
    def accumulate(self):
        ...

    @abc.abstractmethod
    def name(self):
        ...

    def compute(self, *args):
        """Optional pre-processing hook run on device outputs (identity here)."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference: metric/metrics.py Accuracy)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred = _np(pred)
        label = _np(label)
        idx = np.argsort(-pred, axis=-1)[..., : self.maxk]
        if label.ndim == pred.ndim:
            if label.shape[-1] == 1:     # paddle-convention [N, 1] class ids
                label = label[..., 0]
            else:                        # one-hot / soft labels
                label = np.argmax(label, axis=-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        """Accumulates and returns the CURRENT batch's accuracy (paddle contract)."""
        correct = _np(correct)
        num = int(np.prod(correct.shape[:-1]))
        batch = []
        for i, k in enumerate(self.topk):
            c = int(correct[..., :k].any(-1).sum())
            self.total[i] += c
            batch.append(c / max(num, 1))
        self.count += num
        return np.asarray(batch[0] if len(self.topk) == 1 else batch)

    def reset(self):
        self.total = [0] * len(self.topk)
        self.count = 0

    def accumulate(self):
        res = [t / max(self.count, 1) for t in self.total]
        return res[0] if len(self.topk) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (reference: metric/metrics.py Precision)."""

    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(int).reshape(-1)
        labels = _np(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        den = self.tp + self.fp
        return self.tp / den if den else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference: metric/metrics.py Recall)."""

    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def update(self, preds, labels):
        preds = np.rint(_np(preds)).astype(int).reshape(-1)
        labels = _np(labels).astype(int).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        den = self.tp + self.fn
        return self.tp / den if den else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC AUC via threshold buckets (reference: metric/metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def update(self, preds, labels):
        preds = _np(preds)
        if preds.ndim == 2:              # [N, 2] probs -> positive-class prob
            preds = preds[:, 1]
        labels = _np(labels).reshape(-1).astype(bool)
        idx = np.clip((preds.reshape(-1) * self.num_thresholds).astype(int), 0,
                      self.num_thresholds)
        np.add.at(self._stat_pos, idx[labels], 1)
        np.add.at(self._stat_neg, idx[~labels], 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        # sweep thresholds from high to low, trapezoid rule
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        den = tot_pos * tot_neg
        return float(auc / den) if den else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference: metric/metrics.py accuracy)."""
    pred = _np(input)
    lab = _np(label).reshape(-1)
    idx = np.argsort(-pred, axis=-1)[..., :k].reshape(len(lab), k)
    correct_n = (idx == lab[:, None]).any(-1).sum()
    from ..ops.creation import to_tensor
    return to_tensor(np.asarray(correct_n / max(len(lab), 1), np.float32))
