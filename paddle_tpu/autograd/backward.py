"""Reverse-mode tape execution.

Reference analog: ``egr::RunBackward`` (paddle/fluid/eager/backward.cc:106) — build an
in-degree map over the GradNode DAG, then queue-driven topological execution with
GradTensorHolder accumulation; ``general_grad.h`` drives the partial-graph
``paddle.grad()`` variant. Here a "grad node" is a ``jax.vjp`` closure recorded at
forward time (core/tensor.py), so executing a node is one call.

``create_graph=True`` routes the vjp calls and cotangent adds back through
:func:`~paddle_tpu.core.tensor.dispatch`, so the backward pass itself is recorded on the
tape — that is how double grad works (the analog of the reference's generated
double-grad ops).
"""
from __future__ import annotations

from collections import defaultdict, deque

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Node, Tensor, dispatch, no_grad


def _zero_ct(aval):
    if jnp.issubdtype(aval.dtype, jnp.inexact):
        return jnp.zeros(aval.shape, aval.dtype)
    return np.zeros(aval.shape, jax.dtypes.float0)


def _is_float0(x):
    return isinstance(x, np.ndarray) and x.dtype == jax.dtypes.float0


class _Engine:
    def __init__(self, retain_graph: bool, create_graph: bool, sink):
        self.retain_graph = retain_graph or create_graph
        self.create_graph = create_graph
        self.sink = sink  # sink(tensor, cotangent) — receives raw value or Tensor
        self.node_cts: dict[int, list] = {}
        self.pending: dict[int, int] = defaultdict(int)
        self.nodes: dict[int, Node] = {}
        self.ready: deque = deque()

    # -- cotangent algebra (raw arrays fast path; Tensors when create_graph) --
    def _add(self, a, b):
        if self.create_graph:
            a = a if isinstance(a, Tensor) else Tensor(a)
            b = b if isinstance(b, Tensor) else Tensor(b)
            a.stop_gradient = a.stop_gradient and a._node is None
            return dispatch(jnp.add, (a, b), {}, name="grad_accumulate")
        return a + b

    def _call_vjp(self, node: Node, out_ct):
        if self.create_graph and node.fwd_fn is not None:
            # Re-derive the vjp with the original inputs as live tape tensors, so the
            # backward computation itself is differentiable (double grad). This is the
            # analog of the reference's generated double-grad ops referencing forward
            # inputs through the autograd graph rather than through saved residuals.
            out_ct = jax.tree_util.tree_map(
                lambda c: c if isinstance(c, Tensor) or _is_float0(c)
                else Tensor(c, stop_gradient=False),
                out_ct, is_leaf=lambda x: isinstance(x, Tensor) or _is_float0(x))

            def grad_fn(inputs, ct):
                _, vjp = jax.vjp(node.fwd_fn, *inputs)
                return vjp(ct)

            return dispatch(grad_fn, (tuple(node.parents), out_ct), {},
                            name=f"{node.name}_grad")
        if self.create_graph:
            def run(ct):
                return node.vjp_fn(ct)
            out_ct2 = jax.tree_util.tree_map(
                lambda c: c if isinstance(c, Tensor) or _is_float0(c)
                else Tensor(c, stop_gradient=False),
                out_ct, is_leaf=lambda x: isinstance(x, Tensor) or _is_float0(x))
            return dispatch(run, (out_ct2,), {}, name=f"{node.name}_grad")
        return node.vjp_fn(out_ct)

    def seed(self, node: Node, idx: int, ct):
        nid = id(node)
        self.nodes[nid] = node
        cts = self.node_cts.setdefault(nid, [None] * len(node.out_avals))
        cts[idx] = ct if cts[idx] is None else self._add(cts[idx], ct)

    def count_edges(self):
        seen = set()
        stack = [self.nodes[nid] for nid in self.node_cts]
        reach = []
        while stack:
            n = stack.pop()
            if id(n) in seen:
                continue
            seen.add(id(n))
            reach.append(n)
            for p in n.parents:
                if p._node is not None:
                    self.pending[id(p._node)] += 1
                    stack.append(p._node)
        for n in reach:
            self.nodes[id(n)] = n
        self.ready = deque(
            n for n in reach if self.pending[id(n)] == 0 and id(n) in self.node_cts)

    def run(self):
        processed = set()
        while self.ready:
            node = self.ready.popleft()
            if id(node) in processed:
                continue
            processed.add(id(node))
            cts = self.node_cts.pop(id(node), None)
            if cts is None:
                continue
            if node.vjp_fn is None:
                raise RuntimeError(
                    "trying to run backward through the graph a second time; "
                    "use backward(retain_graph=True)")
            full = [c if c is not None else _zero_ct(a)
                    for c, a in zip(cts, node.out_avals)]
            out_ct = jax.tree_util.tree_unflatten(node.out_treedef, full)
            in_cts = self._call_vjp(node, out_ct)
            if not self.retain_graph:
                node.vjp_fn = None
            for ref, aval, c in zip(node.outputs, node.out_avals, full):
                t = ref() if ref is not None else None
                if (t is not None and t._retain_grads
                        and jnp.issubdtype(aval.dtype, jnp.inexact)):
                    self.sink(t, c)
            for parent, ct in zip(node.parents, in_cts):
                if _is_float0(ct):
                    continue
                for hook in parent._hooks:
                    res = hook(ct if isinstance(ct, Tensor) else Tensor(ct))
                    if res is not None:
                        ct = res
                if parent._node is None:
                    self.sink(parent, ct)
                else:
                    self.seed(parent._node, parent._out_index, ct)
                    self.pending[id(parent._node)] -= 1
                    if self.pending[id(parent._node)] == 0:
                        self.ready.append(parent._node)


def _as_value(ct):
    return ct._value if isinstance(ct, Tensor) else ct


def _accumulate_grad(t: Tensor, ct):
    ct = _as_value(ct)
    if t.grad is None:
        t.grad = Tensor(ct)
    else:
        t.grad._value = t.grad._value + ct


def _seed_roots(engine: _Engine, tensors, grad_tensors):
    for t, g in zip(tensors, grad_tensors or [None] * len(tensors)):
        if not isinstance(t, Tensor):
            raise TypeError(f"backward root must be Tensor, got {type(t)}")
        if t.stop_gradient:
            raise RuntimeError("backward() on a tensor with stop_gradient=True")
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "grad can be implicitly created only for scalar outputs; "
                    f"got shape {t.shape}")
            ct = jnp.ones(t._value.shape, t._value.dtype)
        else:
            ct = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        if t._node is None:
            engine.sink(t, ct)
        else:
            engine.seed(t._node, t._out_index, ct)


def run_backward(tensors, grad_tensors=None, retain_graph: bool = False):
    """``Tensor.backward()`` entry: accumulate ``.grad`` on leaf tensors."""
    engine = _Engine(retain_graph, False, _accumulate_grad)
    with no_grad():
        _seed_roots(engine, tensors, grad_tensors)
        engine.count_edges()
        engine.run()


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph: bool = False, only_inputs: bool = True,
         allow_unused: bool = False):
    """paddle.grad — gradients of ``outputs`` w.r.t. ``inputs`` without touching .grad."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        retain_graph = create_graph

    captured: dict[int, object] = {}
    want = {id(t): t for t in inputs}
    # Seed capture through distinct proxy leaves: mark inputs so the engine sink
    # collects their cotangents. Non-leaf inputs are captured via retain_grads plumbing.
    saved_retain = [(t, t._retain_grads) for t in inputs]
    for t in inputs:
        t._retain_grads = True

    def sink(t, ct):
        if id(t) in want:
            prev = captured.get(id(t))
            if prev is None:
                captured[id(t)] = ct
            else:
                captured[id(t)] = engine._add(prev, ct)
        # deliberately do NOT touch .grad

    engine = _Engine(bool(retain_graph), create_graph, sink)
    try:
        if create_graph:
            _seed_roots(engine, outputs, grad_outputs)
            engine.count_edges()
            engine.run()
        else:
            with no_grad():
                _seed_roots(engine, outputs, grad_outputs)
                engine.count_edges()
                engine.run()
    finally:
        for t, r in saved_retain:
            t._retain_grads = r

    results = []
    for t in inputs:
        ct = captured.get(id(t))
        if ct is None:
            if not allow_unused:
                raise RuntimeError(
                    "one of the inputs was not used in the graph; "
                    "set allow_unused=True to return None for it")
            results.append(None)
        elif isinstance(ct, Tensor):
            results.append(ct)
        else:
            results.append(Tensor(ct))
    return results
