"""Autograd API — paddle.autograd analog.

backward / grad ride the tape engine (backward.py); PyLayer lets users define custom
forward/backward pairs (reference: python/paddle/autograd/py_layer.py); the functional
jacobian/hessian ride jax.jacfwd/jacrev directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import (
    Tensor, Node, dispatch, no_grad, enable_grad, is_grad_enabled,
    set_grad_enabled, functional_mode,
)
from .backward import run_backward, grad


def backward(tensors, grad_tensors=None, retain_graph=False):
    if not isinstance(tensors, (list, tuple)):
        tensors = [tensors]
    if grad_tensors is not None and not isinstance(grad_tensors, (list, tuple)):
        grad_tensors = [grad_tensors]
    run_backward(tensors, grad_tensors, retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayer:
    """Custom op with user-defined backward.

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle_tpu.exp(x)
            ctx.save_for_backward(y)
            return y
        @staticmethod
        def backward(ctx, dy):
            (y,) = ctx.saved_tensor()
            return dy * y
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)

        single = not isinstance(out, (list, tuple))
        outs = [out] if single else list(out)
        tensor_outs = [o for o in outs if isinstance(o, Tensor)]

        diff_inputs = [a for a in jax.tree_util.tree_leaves(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
            if isinstance(a, Tensor) and not a.stop_gradient]

        if not is_grad_enabled() or not diff_inputs:
            return out

        out_avals = [jax.ShapeDtypeStruct(tuple(o.shape), o._value.dtype)
                     for o in tensor_outs]
        import jax.tree_util as jtu
        _, out_treedef = jtu.tree_flatten([0] * len(tensor_outs))

        def vjp_fn(out_cts):
            with no_grad():
                ct_tensors = [Tensor(c) for c in out_cts]
                res = cls.backward(ctx, *ct_tensors)
                if not isinstance(res, (list, tuple)):
                    res = (res,)
                if len(res) != len(diff_inputs):
                    raise RuntimeError(
                        f"PyLayer.backward returned {len(res)} grads for "
                        f"{len(diff_inputs)} differentiable inputs")
                vals = []
                for r, inp in zip(res, diff_inputs):
                    if r is None:
                        vals.append(jnp.zeros(tuple(inp.shape), inp._value.dtype))
                    else:
                        vals.append(r._value if isinstance(r, Tensor) else jnp.asarray(r))
                return tuple(vals)

        node = Node(vjp_fn, diff_inputs, out_treedef, out_avals, cls.__name__)
        import weakref
        for i, o in enumerate(tensor_outs):
            o.stop_gradient = False
            o._node = node
            o._out_index = i
            node.outputs.append(weakref.ref(o))
        return out


def jacobian(func, xs, create_graph=False):
    """Functional jacobian via jax.jacrev on the value level."""
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    vals = [x._value for x in xs_list]

    def fn(*vs):
        with functional_mode():
            ts = [Tensor(v, stop_gradient=False) for v in vs]
            out = func(*ts) if len(ts) > 1 else func(ts[0])
            return out._value if isinstance(out, Tensor) else out

    jac = jax.jacrev(fn, argnums=tuple(range(len(vals))))(*vals)
    if isinstance(xs, (list, tuple)):
        return jax.tree_util.tree_map(Tensor, jac)
    return Tensor(jac[0]) if isinstance(jac, tuple) else Tensor(jac)


def hessian(func, xs, create_graph=False):
    xs_list = xs if isinstance(xs, (list, tuple)) else [xs]
    vals = [x._value for x in xs_list]

    def fn(*vs):
        with functional_mode():
            ts = [Tensor(v, stop_gradient=False) for v in vs]
            out = func(*ts) if len(ts) > 1 else func(ts[0])
            return (out._value if isinstance(out, Tensor) else out).sum()

    hes = jax.hessian(fn, argnums=tuple(range(len(vals))))(*vals)
    if isinstance(xs, (list, tuple)):
        return jax.tree_util.tree_map(Tensor, hes)
    h = hes[0][0] if isinstance(hes, tuple) else hes
    return Tensor(h)


__all__ = [
    "backward", "grad", "PyLayer", "PyLayerContext", "no_grad", "enable_grad",
    "is_grad_enabled", "set_grad_enabled", "jacobian", "hessian",
]


class saved_tensors_hooks:
    """Context manager installing pack/unpack hooks on tensors saved for
    backward (reference: autograd/saved_tensors_hooks.py — used for CPU
    offload / compression of activations). Our tape saves tensors inside
    jax.vjp residuals, which XLA already manages; the hooks fire for
    PyLayer's explicit save_for_backward path."""

    _active = None

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        saved_tensors_hooks._active = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        saved_tensors_hooks._active = None
        return False


__all__.append("saved_tensors_hooks")
