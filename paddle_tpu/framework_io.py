"""paddle.save / paddle.load analog.

Reference: python/paddle/framework/io.py:773/:1020 — pickled state_dicts with tensors
converted to numpy. Same wire idea here: tensors serialize as (numpy array, dtype name);
bfloat16/fp8 round-trip through ml_dtypes views.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp

from .core.tensor import Tensor

_SENTINEL = "__paddle_tpu_tensor__"


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._value)
        return {_SENTINEL: True, "data": arr, "stop_gradient": obj.stop_gradient,
                "name": obj.name}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        packed = [_pack(v) for v in obj]
        return packed if isinstance(obj, list) else tuple(packed)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get(_SENTINEL):
            if return_numpy:
                return obj["data"]
            t = Tensor(jnp.asarray(obj["data"]), stop_gradient=obj["stop_gradient"])
            t.name = obj.get("name")
            return t
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_unpack(v, return_numpy) for v in obj]
    if isinstance(obj, tuple):
        return tuple(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **kwargs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
