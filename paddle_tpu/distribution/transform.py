"""paddle.distribution.transform — submodule namespace for the transform
classes (reference: python/paddle/distribution/transform.py; the classes live
in distribution/__init__.py here, same objects re-exported)."""
from . import (  # noqa: F401
    Transform, AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    IndependentTransform, PowerTransform, ReshapeTransform, SigmoidTransform,
    SoftmaxTransform, StackTransform, StickBreakingTransform, TanhTransform,
)

__all__ = [
    'Transform', 'AbsTransform', 'AffineTransform', 'ChainTransform',
    'ExpTransform', 'IndependentTransform', 'PowerTransform',
    'ReshapeTransform', 'SigmoidTransform', 'SoftmaxTransform',
    'StackTransform', 'StickBreakingTransform', 'TanhTransform',
]
