"""paddle.distribution analog (reference: python/paddle/distribution/ —
Distribution base, Normal/Uniform/Categorical/Bernoulli/Beta/Dirichlet/Gamma/
Exponential/Laplace/LogNormal/Multinomial/Gumbel/Geometric/Cauchy/StudentT,
TransformedDistribution + transforms, kl_divergence registry).

TPU-native: sampling rides jax.random with the framework's global RNG stream
(core/random.py), log_prob/entropy are jnp expressions flowing through
dispatch so they differentiate like any other op."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..core import random as _random

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli", "Beta",
    "Dirichlet", "Gamma", "Exponential", "Laplace", "LogNormal", "Multinomial",
    "Gumbel", "Geometric", "Cauchy", "StudentT", "Poisson", "ExponentialFamily",
    "TransformedDistribution", "Independent", "kl_divergence", "register_kl",
]


def _val(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jax.Array) else x


def _wrap(fn, args, name):
    return dispatch(fn, args, {}, name=name)


class Distribution:
    """Reference: distribution/distribution.py Distribution base."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        def fn(lp):
            return jnp.exp(lp)
        return _wrap(fn, (self.log_prob(value),), "prob")

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape):
        return tuple(sample_shape) + self._batch_shape + self._event_shape


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        # keep the caller's Tensors so rsample/log_prob gradients flow to them
        self._loc_t = loc if isinstance(loc, Tensor) else None
        self._scale_t = scale if isinstance(scale, Tensor) else None
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        key = _random.next_key()
        eps = jax.random.normal(key, self._extend_shape(shape),
                                dtype=jnp.result_type(self.loc.dtype, jnp.float32))
        return Tensor(self.loc + self.scale * eps, stop_gradient=True)

    def rsample(self, shape=()):
        key = _random.next_key()
        eps = jax.random.normal(key, self._extend_shape(shape))
        loc = self._loc_t if self._loc_t is not None else Tensor(self.loc)
        scale = (self._scale_t if self._scale_t is not None
                 else Tensor(self.scale))

        def fn(l, s):
            return l + s * eps
        return _wrap(fn, (loc, scale), "normal_rsample")

    def log_prob(self, value):
        loc = self._loc_t if self._loc_t is not None else Tensor(self.loc)
        scale = (self._scale_t if self._scale_t is not None
                 else Tensor(self.scale))

        def fn(v, l, s):
            return (-((v - l) ** 2) / (2 * s ** 2)
                    - jnp.log(s) - 0.5 * math.log(2 * math.pi))
        return _wrap(fn, (value, loc, scale), "normal_log_prob")

    def entropy(self):
        def fn():
            return jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale),
                self.batch_shape)
        return Tensor(fn())

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _val(low)
        self.high = _val(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor((self.high - self.low) ** 2 / 12)

    def sample(self, shape=()):
        key = _random.next_key()
        u = jax.random.uniform(key, self._extend_shape(shape))
        return Tensor(self.low + (self.high - self.low) * u, stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            inside = (v >= self.low) & (v < self.high)
            return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)
        return _wrap(fn, (value,), "uniform_log_prob")

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _val(probs)
        super().__init__(self.probs_.shape)

    @property
    def mean(self):
        return Tensor(self.probs_)

    @property
    def variance(self):
        return Tensor(self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.bernoulli(
            key, self.probs_, self._extend_shape(shape)).astype(jnp.float32),
            stop_gradient=True)

    def rsample(self, shape=(), temperature=1.0):
        key = _random.next_key()
        u = jax.random.uniform(key, self._extend_shape(shape), minval=1e-7,
                               maxval=1 - 1e-7)
        logits = jnp.log(self.probs_) - jnp.log1p(-self.probs_)
        g = jnp.log(u) - jnp.log1p(-u)
        return Tensor(jax.nn.sigmoid((logits + g) / temperature))

    def log_prob(self, value):
        def fn(v):
            p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
            return v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
        return _wrap(fn, (value,), "bernoulli_log_prob")

    def entropy(self):
        p = jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _val(logits)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs_normalized(self):
        return jax.nn.softmax(self.logits, -1)

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.categorical(
            key, self.logits, shape=tuple(shape) + self.batch_shape),
            stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            logp = jax.nn.log_softmax(self.logits, -1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], -1)[..., 0]
        return _wrap(fn, (value,), "categorical_log_prob")

    def probs(self, value):
        def fn(v):
            p = jax.nn.softmax(self.logits, -1)
            return jnp.take_along_axis(p, v.astype(jnp.int32)[..., None],
                                       -1)[..., 0]
        return _wrap(fn, (value,), "categorical_probs")

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(jnp.exp(logp) * logp, -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _val(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        key = _random.next_key()
        logits = jnp.log(jnp.clip(self.probs_, 1e-12))
        draws = jax.random.categorical(
            key, logits, shape=(self.total_count,) + tuple(shape)
            + self.batch_shape)
        k = self.probs_.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(0)
        return Tensor(counts, stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            logp = jnp.log(jnp.clip(self.probs_, 1e-12))
            return (jax.scipy.special.gammaln(self.total_count + 1.0)
                    - jnp.sum(jax.scipy.special.gammaln(v + 1.0), -1)
                    + jnp.sum(v * logp, -1))
        return _wrap(fn, (value,), "multinomial_log_prob")

    def entropy(self):
        # Monte-Carlo-free upper-bound style approximation is out of scope;
        # exact sum over support is exponential — match reference by raising
        raise NotImplementedError


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _val(alpha)
        self.beta = _val(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        tot = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (tot ** 2 * (tot + 1)))

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.beta(key, self.alpha, self.beta,
                                      self._extend_shape(shape)),
                      stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            return ((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v)
                    - _betaln(self.alpha, self.beta))
        return _wrap(fn, (value,), "beta_log_prob")

    def entropy(self):
        a, b = self.alpha, self.beta
        dg = jax.scipy.special.digamma
        return Tensor(_betaln(a, b) - (a - 1) * dg(a) - (b - 1) * dg(b)
                      + (a + b - 2) * dg(a + b))


def _betaln(a, b):
    g = jax.scipy.special.gammaln
    return g(a) + g(b) - g(a + b)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _val(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a = self.concentration
        a0 = jnp.sum(a, -1, keepdims=True)
        return Tensor(a * (a0 - a) / (a0 ** 2 * (a0 + 1)))

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.dirichlet(key, self.concentration,
                                           tuple(shape) + self.batch_shape),
                      stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            a = self.concentration
            g = jax.scipy.special.gammaln
            return (jnp.sum((a - 1) * jnp.log(v), -1)
                    + g(jnp.sum(a, -1)) - jnp.sum(g(a), -1))
        return _wrap(fn, (value,), "dirichlet_log_prob")

    def entropy(self):
        a = self.concentration
        g, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        return Tensor(jnp.sum(g(a), -1) - g(a0) + (a0 - k) * dg(a0)
                      - jnp.sum((a - 1) * dg(a), -1))


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _val(concentration)
        self.rate = _val(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / self.rate ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        g = jax.random.gamma(key, self.concentration, self._extend_shape(shape))
        return Tensor(g / self.rate, stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            a, r = self.concentration, self.rate
            return (a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                    - jax.scipy.special.gammaln(a))
        return _wrap(fn, (value,), "gamma_log_prob")

    def entropy(self):
        a, r = self.concentration, self.rate
        g, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
        return Tensor(a - jnp.log(r) + g(a) + (1 - a) * dg(a))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / self.rate ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.exponential(
            key, self._extend_shape(shape)) / self.rate, stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            return jnp.log(self.rate) - self.rate * v
        return _wrap(fn, (value,), "exponential_log_prob")

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(self.loc + self.scale * jax.random.laplace(
            key, self._extend_shape(shape)), stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            return (-jnp.abs(v - self.loc) / self.scale
                    - jnp.log(2 * self.scale))
        return _wrap(fn, (value,), "laplace_log_prob")

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        self._normal = Normal(loc, scale)
        super().__init__(self._normal.batch_shape)

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        return Tensor((jnp.exp(self.scale ** 2) - 1)
                      * jnp.exp(2 * self.loc + self.scale ** 2))

    def sample(self, shape=()):
        return Tensor(jnp.exp(self._normal.sample(shape)._value),
                      stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            logv = jnp.log(v)
            var = self.scale ** 2
            return (-((logv - self.loc) ** 2) / (2 * var) - logv
                    - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))
        return _wrap(fn, (value,), "lognormal_log_prob")

    def entropy(self):
        return Tensor(self.loc + 0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * np.euler_gamma)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * self.scale ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(self.loc + self.scale * jax.random.gumbel(
            key, self._extend_shape(shape)), stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)
        return _wrap(fn, (value,), "gumbel_log_prob")

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1 + np.euler_gamma)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k >= 0 (failures before first success)."""

    def __init__(self, probs, name=None):
        self.probs_ = _val(probs)
        super().__init__(self.probs_.shape)

    @property
    def mean(self):
        return Tensor((1 - self.probs_) / self.probs_)

    @property
    def variance(self):
        return Tensor((1 - self.probs_) / self.probs_ ** 2)

    def sample(self, shape=()):
        key = _random.next_key()
        u = jax.random.uniform(key, self._extend_shape(shape), minval=1e-7,
                               maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)),
                      stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            return v * jnp.log1p(-self.probs_) + jnp.log(self.probs_)
        return _wrap(fn, (value,), "geometric_log_prob")

    def entropy(self):
        p = self.probs_
        q = 1 - p
        return Tensor(-(q * jnp.log(q) + p * jnp.log(p)) / p)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(self.loc + self.scale * jax.random.cauchy(
            key, self._extend_shape(shape)), stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            z = (v - self.loc) / self.scale
            return -jnp.log(math.pi * self.scale * (1 + z ** 2))
        return _wrap(fn, (value,), "cauchy_log_prob")

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale))


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _val(df)
        self.loc = _val(loc)
        self.scale = _val(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.where(self.df > 1, self.loc, jnp.nan))

    @property
    def variance(self):
        v = jnp.where(self.df > 2, self.scale ** 2 * self.df / (self.df - 2),
                      jnp.inf)
        return Tensor(jnp.where(self.df > 1, v, jnp.nan))

    def sample(self, shape=()):
        key = _random.next_key()
        t = jax.random.t(key, self.df, self._extend_shape(shape))
        return Tensor(self.loc + self.scale * t, stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            g = jax.scipy.special.gammaln
            d = self.df
            z = (v - self.loc) / self.scale
            return (g((d + 1) / 2) - g(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                    - (d + 1) / 2 * jnp.log1p(z ** 2 / d))
        return _wrap(fn, (value,), "studentt_log_prob")


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _val(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        key = _random.next_key()
        return Tensor(jax.random.poisson(key, self.rate,
                                         self._extend_shape(shape)).astype(
            jnp.float32), stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            return (v * jnp.log(self.rate) - self.rate
                    - jax.scipy.special.gammaln(v + 1))
        return _wrap(fn, (value,), "poisson_log_prob")


class ExponentialFamily(Distribution):
    """Parity base class (reference distribution/exponential_family.py)."""


class Independent(Distribution):
    """Reinterprets batch dims as event dims (reference
    distribution/independent.py)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = reinterpreted_batch_rank
        b = base.batch_shape
        super().__init__(b[:len(b) - self.rank],
                         b[len(b) - self.rank:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)

        def fn(l):
            return jnp.sum(l, axis=tuple(range(-self.rank, 0)))
        return _wrap(fn, (lp,), "independent_log_prob")

    def entropy(self):
        ent = self.base.entropy()

        def fn(e):
            return jnp.sum(e, axis=tuple(range(-self.rank, 0)))
        return _wrap(fn, (ent,), "independent_entropy")


# ---------------------------------------------------------------------------
# transforms + TransformedDistribution
# ---------------------------------------------------------------------------

class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _val(loc)
        self.scale = _val(scale)

    def forward(self, x):
        return self.loc + self.scale * x

    def inverse(self, y):
        return (y - self.loc) / self.scale

    def forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    def forward(self, x):
        return jnp.exp(x)

    def inverse(self, y):
        return jnp.log(y)

    def forward_log_det_jacobian(self, x):
        return x


class SigmoidTransform(Transform):
    def forward(self, x):
        return jax.nn.sigmoid(x)

    def inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = (transforms if isinstance(transforms, (list, tuple))
                           else [transforms])
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)._value
        for t in self.transforms:
            x = t.forward(x)
        return Tensor(x, stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            lp = 0.0
            y = v
            for t in reversed(self.transforms):
                x = t.inverse(y)
                lp = lp - t.forward_log_det_jacobian(x)
                y = x
            return lp + _val(self.base.log_prob(Tensor(y)))
        return _wrap(fn, (value,), "transformed_log_prob")


# ---------------------------------------------------------------------------
# KL divergence registry
# ---------------------------------------------------------------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """Reference: distribution/kl.py register_kl."""
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"no KL registered for ({type(p).__name__}, {type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_p, var_q = p.scale ** 2, q.scale ** 2
    return Tensor(0.5 * (var_p / var_q + (q.loc - p.loc) ** 2 / var_q
                         - 1 + jnp.log(var_q / var_p)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    lp = jax.nn.log_softmax(p.logits, -1)
    lq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(lp) * (lp - lq), -1))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    a = jnp.clip(p.probs_, 1e-7, 1 - 1e-7)
    b = jnp.clip(q.probs_, 1e-7, 1 - 1e-7)
    return Tensor(a * (jnp.log(a) - jnp.log(b))
                  + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(p.rate) - jnp.log(q.rate) + r - 1)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    dg = jax.scipy.special.digamma
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    t1 = _betaln(a2, b2) - _betaln(a1, b1)
    return Tensor(t1 + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                  + (a2 - a1 + b2 - b1) * dg(a1 + b1))


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    g, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
    a1, r1, a2, r2 = p.concentration, p.rate, q.concentration, q.rate
    return Tensor((a1 - a2) * dg(a1) - g(a1) + g(a2)
                  + a2 * (jnp.log(r1) - jnp.log(r2)) + a1 * (r2 - r1) / r1)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    g, dg = jax.scipy.special.gammaln, jax.scipy.special.digamma
    a, b = p.concentration, q.concentration
    a0 = jnp.sum(a, -1)
    return Tensor(g(a0) - jnp.sum(g(a), -1) - g(jnp.sum(b, -1))
                  + jnp.sum(g(b), -1)
                  + jnp.sum((a - b) * (dg(a) - dg(a0)[..., None]), -1))


# ---------------------------------------------------------------------------
# additional distributions (reference: python/paddle/distribution/)
# ---------------------------------------------------------------------------

class Chi2(Gamma):
    """Chi-squared: Gamma(df/2, 1/2) (reference: distribution/chi2.py)."""

    def __init__(self, df, name=None):
        self.df = _val(df)
        super().__init__(self.df / 2.0, 0.5)


class Binomial(Distribution):
    """Reference: distribution/binomial.py."""

    def __init__(self, total_count, probs, name=None):
        self.total_count = _val(total_count)
        self.probs_ = _val(probs)
        super().__init__(jnp.broadcast_shapes(jnp.shape(self.total_count),
                                              self.probs_.shape))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        key = _random.next_key()
        out = jax.random.binomial(key, self.total_count, self.probs_,
                                  shape=self._extend_shape(shape))
        return Tensor(out, stop_gradient=True)

    def log_prob(self, value):
        def fn(v):
            n, p = self.total_count, jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
            logc = (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1))
            return logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p)
        return _wrap(fn, (value,), "binomial_log_prob")

    def entropy(self):
        # sum over the support (exact; support is static)
        n = int(np.max(self.total_count))
        ks = jnp.arange(n + 1, dtype=jnp.float32)
        lp = _val(self.log_prob(Tensor(
            jnp.broadcast_to(ks.reshape((-1,) + (1,) * len(self.batch_shape)),
                             (n + 1,) + tuple(self.batch_shape)))))
        valid = ks.reshape((-1,) + (1,) * len(self.batch_shape)) \
            <= self.total_count
        p = jnp.where(valid, jnp.exp(lp), 0.0)
        return Tensor(-jnp.sum(jnp.where(valid, p * lp, 0.0), axis=0))


class ContinuousBernoulli(Distribution):
    """Reference: distribution/continuous_bernoulli.py."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs_ = jnp.clip(_val(probs), 1e-6, 1 - 1e-6)
        self._lims = lims
        super().__init__(self.probs_.shape)

    def _log_norm_const(self):
        lam = self.probs_
        lo, hi = self._lims
        # C(λ) = 2 atanh(1-2λ) / (1-2λ), with the λ→1/2 limit = 2
        safe = jnp.where((lam < lo) | (lam > hi), lam, 0.4)
        c = 2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe)
        # 2nd-order Taylor around 1/2 for the unstable band
        x = lam - 0.5
        taylor = 2.0 + (16.0 / 3.0) * x ** 2
        return jnp.log(jnp.where((lam < lo) | (lam > hi), c, taylor))

    @property
    def mean(self):
        lam = self.probs_
        lo, hi = self._lims
        safe = jnp.where((lam < lo) | (lam > hi), lam, 0.4)
        m = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
        return Tensor(jnp.where((lam < lo) | (lam > hi), m, 0.5))

    def sample(self, shape=()):
        key = _random.next_key()
        u = jax.random.uniform(key, self._extend_shape(shape))
        return Tensor(self.icdf(Tensor(u))._value, stop_gradient=True)

    rsample = sample

    def icdf(self, value):
        def fn(u):
            lam = self.probs_
            lo, hi = self._lims
            safe = jnp.where((lam < lo) | (lam > hi), lam, 0.4)
            num = jnp.log1p(u * (2 * safe - 1) / (1 - safe))
            den = jnp.log(safe) - jnp.log1p(-safe)
            return jnp.where((lam < lo) | (lam > hi), num / den, u)
        return _wrap(fn, (value,), "cb_icdf")

    def log_prob(self, value):
        def fn(v):
            lam = self.probs_
            return (v * jnp.log(lam) + (1 - v) * jnp.log1p(-lam)
                    + self._log_norm_const())
        return _wrap(fn, (value,), "cb_log_prob")


class MultivariateNormal(Distribution):
    """Reference: distribution/multivariate_normal.py. Parameterize with
    covariance_matrix, precision_matrix, or scale_tril."""

    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _val(loc)
        given = [x is not None
                 for x in (covariance_matrix, precision_matrix, scale_tril)]
        if sum(given) != 1:
            raise ValueError("give exactly one of covariance_matrix, "
                             "precision_matrix, scale_tril")
        if scale_tril is not None:
            self._tril = _val(scale_tril)
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(_val(covariance_matrix))
        else:
            prec = _val(precision_matrix)
            self._tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        # batch shape broadcasts loc against the matrix batch (torch semantics)
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self._tril.shape[:-2])
        self.loc = jnp.broadcast_to(self.loc, batch + self.loc.shape[-1:])
        self._tril = jnp.broadcast_to(self._tril,
                                      batch + self._tril.shape[-2:])
        super().__init__(batch, self.loc.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def covariance_matrix(self):
        return Tensor(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    @property
    def variance(self):
        return Tensor(jnp.sum(self._tril ** 2, axis=-1))

    def sample(self, shape=()):
        key = _random.next_key()
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        eps = jax.random.normal(
            key, shape + tuple(self.batch_shape) + tuple(self.event_shape))
        out = self.loc + jnp.einsum("...ij,...j->...i", self._tril, eps)
        return Tensor(out, stop_gradient=True)

    rsample = sample

    def log_prob(self, value):
        def fn(v):
            d = v.shape[-1]
            diff = v - self.loc
            tril = jnp.broadcast_to(self._tril, diff.shape[:-1] + (d, d))
            sol = jax.scipy.linalg.solve_triangular(
                tril, diff[..., None], lower=True)[..., 0]
            maha = jnp.sum(sol ** 2, -1)
            logdet = jnp.sum(
                jnp.log(jnp.diagonal(self._tril, axis1=-2, axis2=-1)), -1)
            return -0.5 * (maha + d * jnp.log(2 * jnp.pi)) - logdet
        return _wrap(fn, (value,), "mvn_log_prob")

    def entropy(self):
        d = self.loc.shape[-1]
        logdet = jnp.sum(
            jnp.log(jnp.diagonal(self._tril, axis1=-2, axis2=-1)), -1)
        return Tensor(0.5 * d * (1 + jnp.log(2 * jnp.pi)) + logdet)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    d = p.loc.shape[-1]
    qt, pt = q._tril, p._tril
    sol = jax.scipy.linalg.solve_triangular(
        qt, pt, lower=True)
    tr = jnp.sum(sol ** 2, axis=(-2, -1))
    diff = q.loc - p.loc
    m = jax.scipy.linalg.solve_triangular(qt, diff[..., None],
                                          lower=True)[..., 0]
    maha = jnp.sum(m ** 2, -1)
    logdet_q = jnp.sum(jnp.log(jnp.diagonal(qt, axis1=-2, axis2=-1)), -1)
    logdet_p = jnp.sum(jnp.log(jnp.diagonal(pt, axis1=-2, axis2=-1)), -1)
    return Tensor(0.5 * (tr + maha - d) + logdet_q - logdet_p)


class LKJCholesky(Distribution):
    """LKJ prior over Cholesky factors of correlation matrices (reference:
    distribution/lkj_cholesky.py). Sampling via the onion method."""

    def __init__(self, dim, concentration=1.0, sample_method="onion",
                 name=None):
        if sample_method != "onion":
            raise NotImplementedError(
                f"sample_method={sample_method!r}: only 'onion' is "
                "implemented (cvine draws a different — equally valid — "
                "parameterization)")
        self.dim = int(dim)
        self.sample_method = sample_method
        self.concentration = jnp.asarray(_val(concentration), jnp.float32)
        super().__init__(jnp.shape(self.concentration),
                         (self.dim, self.dim))

    def sample(self, shape=()):
        key = _random.next_key()
        shape = (shape,) if isinstance(shape, int) else tuple(shape)
        d, eta = self.dim, self.concentration
        batch = shape + tuple(self.batch_shape)
        k1, k2 = jax.random.split(key)
        # onion: beta marginals for the norms, spherical directions
        L = jnp.zeros(batch + (d, d))
        L = L.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            beta_a = (d - 1 - i) / 2.0 + eta
            y = jax.random.beta(jax.random.fold_in(k1, i), i / 2.0, beta_a,
                                batch)
            u = jax.random.normal(jax.random.fold_in(k2, i), batch + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            L = L.at[..., i, :i].set(jnp.sqrt(y)[..., None] * u)
            L = L.at[..., i, i].set(jnp.sqrt(1 - y))
        return Tensor(L, stop_gradient=True)

    def log_prob(self, value):
        def fn(L):
            d, eta = self.dim, self.concentration
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            orders = jnp.arange(2, d + 1, dtype=jnp.float32)
            exps = 2 * (eta - 1)[..., None] + (d - orders)[None, :] \
                if jnp.ndim(eta) else 2 * (eta - 1) + (d - orders)
            unnorm = jnp.sum(exps * jnp.log(diag), -1)
            # normalizer: ½(d-1)logπ + logΓ_{d-1}(α-½) - (d-1)logΓ(α),
            # α = η + (d-1)/2, with Γ_p the multivariate gamma
            dm1 = d - 1
            alpha = eta + 0.5 * dm1
            js = jnp.arange(1, dm1 + 1, dtype=jnp.float32)
            mvlgamma = (dm1 * (dm1 - 1) / 4.0) * jnp.log(jnp.pi) + jnp.sum(
                jax.scipy.special.gammaln(
                    (alpha - 0.5)[..., None] + (1.0 - js) / 2.0
                    if jnp.ndim(alpha) else (alpha - 0.5) + (1.0 - js) / 2.0),
                -1)
            logc = (0.5 * dm1 * jnp.log(jnp.pi) + mvlgamma
                    - dm1 * jax.scipy.special.gammaln(alpha))
            return unnorm - logc
        return _wrap(fn, (value,), "lkj_log_prob")


# ---------------------------------------------------------------------------
# additional transforms (reference: python/paddle/distribution/transform.py)
# ---------------------------------------------------------------------------

class AbsTransform(Transform):
    def forward(self, x):
        return jnp.abs(x)

    def inverse(self, y):
        return y  # positive branch, matching the reference


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _val(power)

    def forward(self, x):
        return jnp.power(x, self.power)

    def inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class TanhTransform(Transform):
    def forward(self, x):
        return jnp.tanh(x)

    def inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-7, 1 - 1e-7))

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2), numerically stable form
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t.forward_log_det_jacobian(x)
            x = t.forward(x)
        return total


class IndependentTransform(Transform):
    """Sums the log-det over the trailing `reinterpreted_batch_rank` dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ld = self.base.forward_log_det_jacobian(x)
        return jnp.sum(ld, axis=tuple(range(-self.rank, 0)))


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def forward(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.reshape(x, batch + self.out_event_shape)

    def inverse(self, y):
        batch = y.shape[: y.ndim - len(self.out_event_shape)]
        return jnp.reshape(y, batch + self.in_event_shape)

    def forward_log_det_jacobian(self, x):
        batch = x.shape[: x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch)


class SoftmaxTransform(Transform):
    """y = softmax(x); inverse is log(y) (defined up to an additive const)."""

    def forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def inverse(self, y):
        return jnp.log(y)


class StackTransform(Transform):
    """Applies a list of transforms to slices along `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, x, method):
        slices = jnp.moveaxis(x, self.axis, 0)
        if slices.shape[0] != len(self.transforms):
            raise ValueError(
                f"StackTransform: input has {slices.shape[0]} slices along "
                f"axis {self.axis} but {len(self.transforms)} transforms")
        parts = [getattr(t, method)(s)
                 for t, s in zip(self.transforms, slices)]
        return jnp.moveaxis(jnp.stack(parts), 0, self.axis)

    def forward(self, x):
        return self._map(x, "forward")

    def inverse(self, y):
        return self._map(y, "inverse")

    def forward_log_det_jacobian(self, x):
        return self._map(x, "forward_log_det_jacobian")


class StickBreakingTransform(Transform):
    """Unconstrained R^k -> (k+1)-simplex (reference:
    distribution/transform.py StickBreakingTransform)."""

    def forward(self, x):
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        z = jax.nn.sigmoid(x - offset)
        zpad = jnp.concatenate([z, jnp.ones(x.shape[:-1] + (1,), x.dtype)],
                               -1)
        cum = jnp.cumprod(1 - z, axis=-1)
        cpad = jnp.concatenate([jnp.ones(x.shape[:-1] + (1,), x.dtype), cum],
                               -1)
        return zpad * cpad

    def inverse(self, y):
        k = y.shape[-1] - 1
        cum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - jnp.concatenate(
            [jnp.zeros(y.shape[:-1] + (1,), y.dtype), cum[..., :-1]], -1)
        z = y[..., :-1] / rem
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=y.dtype))
        return jnp.log(z) - jnp.log1p(-z) + offset

    def forward_log_det_jacobian(self, x):
        # y_i = z_i * prod_{j<i}(1-z_j) with z = sigmoid(x - offset);
        # |J| = prod_i z_i(1-z_i) * prod_{j<i}(1-z_j)
        k = x.shape[-1]
        offset = jnp.log(jnp.arange(k, 0, -1, dtype=x.dtype))
        xo = x - offset
        z = jax.nn.sigmoid(xo)
        cum = jnp.cumprod(1 - z, axis=-1)
        cpad = jnp.concatenate(
            [jnp.ones(x.shape[:-1] + (1,), x.dtype), cum[..., :-1]], -1)
        log_dz = -jax.nn.softplus(xo) - jax.nn.softplus(-xo)  # log z(1-z)
        return jnp.sum(log_dz + jnp.log(cpad), -1)


__all__ += [
    "Chi2", "Binomial", "ContinuousBernoulli", "MultivariateNormal",
    "LKJCholesky", "AbsTransform", "PowerTransform", "TanhTransform",
    "ChainTransform", "IndependentTransform", "ReshapeTransform",
    "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
    "Transform", "AffineTransform", "ExpTransform", "SigmoidTransform",
]
