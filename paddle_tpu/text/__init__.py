"""paddle.text analog — ViterbiDecoder + dataset registry.

Reference: python/paddle/text/ (viterbi_decode.py ViterbiDecoder/viterbi_decode,
datasets/ — Imdb, Imikolov, Movielens, Conll05st, UCIHousing, WMT14, WMT16).
Datasets require network downloads; this environment has no egress, so they
raise a clear gating error unless the files are already cached locally.
TPU-native: the Viterbi recursion is a lax.scan over time steps — compiled,
batched, differentiable through the score.
"""
from __future__ import annotations

import os

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..nn.layer_base import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """Batched Viterbi decode (reference: text/viterbi_decode.py:24).

    potentials: (B, T, N) emission scores; transition_params: (N, N);
    lengths: (B,) valid lengths. Returns (scores (B,), paths (B, T))."""
    lens = jnp.asarray(lengths._value if isinstance(lengths, Tensor)
                       else lengths, dtype=jnp.int32)

    def fn(emis, trans):
        B, T, N = emis.shape
        if include_bos_eos_tag:
            # reference semantics: tag N-2 = BOS, N-1 = EOS
            bos_idx, eos_idx = N - 2, N - 1
            init = emis[:, 0] + trans[bos_idx][None, :]
        else:
            init = emis[:, 0]

        def step(alpha, t):
            # alpha: (B, N); candidate scores (B, from N, to N)
            cand = alpha[:, :, None] + trans[None, :, :]
            best_prev = jnp.argmax(cand, axis=1).astype(jnp.int32)  # (B, N)
            alpha_new = jnp.max(cand, axis=1) + emis[:, t]
            # freeze past the sequence end
            active = (t < lens)[:, None]
            alpha_new = jnp.where(active, alpha_new, alpha)
            best_prev = jnp.where(active, best_prev,
                                  jnp.arange(N, dtype=jnp.int32)[None, :])
            return alpha_new, best_prev

        alpha, backptrs = jax.lax.scan(step, init, jnp.arange(1, T))
        # backptrs: (T-1, B, N)
        if include_bos_eos_tag:
            alpha = alpha + trans[:, eos_idx][None, :]
        scores = jnp.max(alpha, axis=1)
        last_tag = jnp.argmax(alpha, axis=1).astype(jnp.int32)  # (B,)

        def back_step(tag, ptr_t):
            # ptr_t: (B, N) for step t; identity pointers past sequence end
            prev = jnp.take_along_axis(ptr_t, tag[:, None], axis=1)[:, 0]
            return prev.astype(jnp.int32), tag

        first, path_rev = jax.lax.scan(back_step, last_tag, backptrs[::-1])
        # path_rev: (T-1, B) tags for t = T-1 .. 1; carry out = tag at t=0
        paths = jnp.concatenate([first[None, :], path_rev[::-1]], axis=0).T
        return scores, paths.astype(jnp.int64)

    return dispatch(fn, (potentials, transition_params), {},
                    name="viterbi_decode")


class ViterbiDecoder(Layer):
    """Reference: text/viterbi_decode.py ViterbiDecoder layer."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)


# ---------------------------------------------------------------------------
# dataset registry — gated (no egress in this environment)
# ---------------------------------------------------------------------------

_DATASET_NAMES = ("Imdb", "Imikolov", "Movielens", "Conll05st", "UCIHousing",
                  "WMT14", "WMT16", "ViterbiDataset")


def _gated_dataset(name):
    class _Gated:
        def __init__(self, *args, data_file=None, **kwargs):
            if data_file is None or not os.path.exists(data_file):
                raise RuntimeError(
                    f"paddle.text dataset {name} needs its archive on disk "
                    "(downloads are disabled in this environment); pass "
                    "data_file=<local path>")
            self.data_file = data_file

    _Gated.__name__ = name
    return _Gated


for _n in _DATASET_NAMES[:-1]:
    globals()[_n] = _gated_dataset(_n)
    __all__.append(_n)
