"""paddle.inference analog — deployment Predictor API.

Reference: paddle/fluid/inference AnalysisPredictor
(api/analysis_predictor.h:101 — load saved model → IR pass pipeline → executor,
zero-copy input/output handles, Config with optimization toggles).

TPU-native: "analysis passes + engine" is XLA — a saved `jax.export` artifact
(paddle_tpu.static.save_inference_model) deserializes to an AOT-compiled
callable; the Predictor owns input binding, device placement, and compiled-call
reuse. No interpreter, no pass pipeline to maintain: the serialized StableHLO
IS the optimized program.
"""
from __future__ import annotations

import os

import numpy as np
import jax

from ..core.tensor import Tensor

__all__ = ["Config", "Predictor", "create_predictor", "PrecisionType"]


class PrecisionType:
    Float32 = "float32"
    Bfloat16 = "bfloat16"
    Half = "float16"
    Int8 = "int8"


class Config:
    """Reference: paddle_infer::Config (api/paddle_analysis_config.h)."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle packs both into one artifact; we accept either arg as prefix
        self.model_path = prog_file or params_file
        self._device = "tpu"
        self._precision = PrecisionType.Float32
        self._enable_profile = False
        self._memory_optim = True

    def set_model(self, prog_file, params_file=None):
        self.model_path = prog_file

    def model_dir(self):
        return self.model_path

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        # GPU knob maps to the accelerator backend (TPU here)
        self._device = "tpu"
        self._precision = precision

    def enable_tpu(self, precision=PrecisionType.Bfloat16):
        self._device = "tpu"
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "tpu"

    def enable_memory_optim(self, flag=True):
        self._memory_optim = flag

    def enable_profile(self):
        self._enable_profile = True

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes; kept for API parity

    def summary(self):
        return (f"Config(model={self.model_path}, device={self._device}, "
                f"precision={self._precision})")


class _IOHandle:
    """Zero-copy-style tensor handle (reference: paddle_infer::Tensor,
    api/paddle_tensor.h)."""

    def __init__(self, name):
        self.name = name
        self._value = None

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        return list(np.asarray(self._value).shape)


class Predictor:
    """Reference: paddle_infer::Predictor (AnalysisPredictor)."""

    def __init__(self, config: Config):
        self.config = config
        path = config.model_path
        if path is None or not (os.path.exists(path)
                                or os.path.exists(path + ".pdmodel")):
            raise FileNotFoundError(f"inference model not found: {path}")
        from ..static import load_inference_model
        self._fn, self._meta = load_inference_model(path, _return_meta=True)
        self._input_names = list(self._meta.get("feed_names", []))
        self._output_names = list(self._meta.get("fetch_names", []))
        self._inputs = {n: _IOHandle(n) for n in self._input_names}
        # persistent handles: users bind them once and read after each run()
        self._outputs = {n: _IOHandle(n) for n in self._output_names}

    def get_input_names(self):
        return list(self._input_names)

    def get_output_names(self):
        return list(self._output_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Either positional (list of arrays → list of arrays, the modern
        paddle_infer.Predictor.run) or via bound handles."""
        if inputs is not None:
            args = [a.numpy() if isinstance(a, Tensor) else np.asarray(a)
                    for a in inputs]
        else:
            args = [self._inputs[n]._value for n in self._input_names]
            missing = [n for n, a in zip(self._input_names, args) if a is None]
            if missing:
                raise RuntimeError(f"inputs not bound: {missing}")
        outs = self._fn(*args)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        outs = [np.asarray(o) for o in outs]
        names = self._output_names or [f"fetch_{i}" for i in range(len(outs))]
        for n, o in zip(names, outs):
            self._outputs.setdefault(n, _IOHandle(n))._value = o
        if inputs is not None:
            return outs
        return True

    def try_shrink_memory(self):
        jax.clear_caches()

    def clone(self):
        return Predictor(self.config)


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


class DataType:
    """reference: paddle_infer DataType enum."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6
    FLOAT64 = 7
    BOOL = 8


class PlaceType:
    """reference: paddle_infer PlaceType enum (kXPU slot carries the TPU)."""
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM = 3


class XpuConfig:
    """Accelerator config bag (reference: paddle_infer XpuConfig). TPU
    memory/stream knobs are PJRT-managed; fields are recorded for parity."""

    def __init__(self):
        self.device_id = 0
        self.l3_size = 0
        self.conv_autotune_level = 0


def get_version():
    from .. import version
    return f"version: {version.full_version}"


def get_num_bytes_of_data_type(dtype):
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2, DataType.FLOAT64: 8, DataType.BOOL: 1}
    return sizes.get(dtype, 4)


def get_trt_compile_version():
    return (0, 0, 0)  # not built with TensorRT (XLA is the engine)


def get_trt_runtime_version():
    return (0, 0, 0)


def _get_phi_kernel_name(op_name):
    return op_name  # one op registry: python name == kernel name


def convert_to_mixed_precision(model_file, params_file, mixed_model_file,
                               mixed_params_file, mixed_precision, backend,
                               keep_io_types=True, black_list=None,
                               white_list=None):
    """reference: inference/convert_to_mixed_precision — rewrite a saved
    model's dtype. jax.export artifacts carry dtypes inside StableHLO, so the
    conversion re-exports at load time via amp; here we copy the artifact and
    record the requested precision for the Predictor to apply."""
    import shutil
    shutil.copy(model_file, mixed_model_file)
    if params_file and params_file != mixed_params_file:
        try:
            shutil.copy(params_file, mixed_params_file)
        except FileNotFoundError:
            pass
    return mixed_model_file


class PredictorPool:
    """reference: paddle_infer PredictorPool — N predictors sharing one
    config for multi-threaded serving."""

    def __init__(self, config, size=1):
        self._preds = [create_predictor(config) for _ in range(max(1, size))]

    def retrieve(self, idx):
        return self._preds[idx % len(self._preds)]


__all__ += ["DataType", "PlaceType", "XpuConfig", "get_version",
            "get_num_bytes_of_data_type", "get_trt_compile_version",
            "get_trt_runtime_version", "convert_to_mixed_precision",
            "PredictorPool", "_get_phi_kernel_name"]


from .llm_engine import LLMEngine, GenerationRequest, RequestOutput  # noqa: E402,F401
__all__ += ["LLMEngine", "GenerationRequest", "RequestOutput"]
