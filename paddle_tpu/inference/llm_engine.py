"""LLM serving engine — continuous batching over compiled decode steps.

Reference analog: the serving path the reference builds from
AnalysisPredictor (paddle/fluid/inference/api/analysis_predictor.h:101) plus
the fused decode kernels
(python/paddle/incubate/nn/functional/block_multihead_attention.py:1,
masked_multihead_attention.py:1) that PaddleNLP's serving stack drives with
dynamic request batching.

TPU-native design — everything is STATIC shapes so two compiled programs
serve the whole engine lifetime:

  * ``max_batch`` fixed slots; each slot owns a [capacity, H, D] region of
    the per-layer KV buffers and a traced length (``SlotKVCache``), so
    ragged sequences share one compiled decode step.
  * one **decode step** program: sample (per-slot temperature/top-p vectors,
    greedy-vs-sample selected per slot in-graph) -> one-token model step
    writing KV at each slot's own position -> next logits. Varying sampling
    params or slot occupancy never recompiles.
  * one **chunked-prefill** program per chunk size: admits a request by
    streaming its prompt through fixed-size chunks into its slot's KV region
    (dynamic_slice/update on the slot axis), returning last-position logits.
    Chunk padding is masked by causality and overwritten by later writes.
  * requests join and leave BETWEEN steps (continuous batching): a finished
    slot is freed at the step boundary and the next queued request admits
    into it while other slots keep decoding.

Logits stay on device between steps; the only per-step host transfer is the
[B] sampled-token vector that streaming callers need anyway.
"""
from __future__ import annotations

import collections
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, functional_mode
from ..models.llama import SlotKVCache, _sample_logits_device

__all__ = ["LLMEngine", "GenerationRequest", "RequestOutput"]


@dataclasses.dataclass
class GenerationRequest:
    request_id: int
    prompt_ids: np.ndarray           # [P] int32
    max_new_tokens: int = 64
    temperature: float = 0.0         # <=0 -> greedy
    top_p: float = 1.0
    eos_token_id: int | None = None


@dataclasses.dataclass
class RequestOutput:
    request_id: int
    token_ids: list
    finished: bool = False
    finish_reason: str | None = None


class _Slot:
    __slots__ = ("req", "generated", "prompt_len")

    def __init__(self, req, prompt_len):
        self.req = req
        self.generated = []
        self.prompt_len = prompt_len


class LLMEngine:
    """Continuous-batching engine over a LlamaForCausalLM (works with
    bf16/fp32 and WeightOnlyLinear-quantized weights; under a mesh the
    programs partition by GSPMD like ``generate()``)."""

    def __init__(self, model, max_batch=4, max_seq_len=None, chunk_size=64,
                 top_k=0, stream_callback=None, horizon=1, speculative_k=1,
                 lookup_ngram=3, mesh=None):
        """``mesh``: a jax Mesh for MULTI-PROCESS serving — engine buffers
        are created as global (replicated) arrays on it so the compiled
        programs can mix them with TP-sharded weights whose groups span
        processes; every process runs the same step() calls (SPMD) and
        reads the same replicated token vector."""
        from ..jit.functional_call import collect_state, read_values

        self.model = model
        c = model.config
        self.B = int(max_batch)
        # decode horizon: tokens decoded per step() call as one compiled
        # lax.scan — amortizes the per-step host sync K-fold at the cost of
        # admitting/retiring requests only every K tokens
        self.horizon = max(1, int(horizon))
        # speculative verify windows (prompt-lookup drafting, NO reference
        # analog — the snapshot has no speculative decoding): each window
        # commits 1 sampled token plus up to speculative_k-1 drafted tokens
        # verified by ONE K-token model call. Drafting runs IN-GRAPH from a
        # device-side token history, so windows compose with `horizon`: one
        # step() = horizon windows = up to horizon*speculative_k tokens per
        # host round-trip. Greedy slots accept token-exactly; sampling
        # slots use rejection-sampling acceptance (distribution-exact for
        # pure temperature sampling; with top-k/top-p the residual re-
        # filters the masked distribution, see _spec_accept).
        self.speculative_k = max(1, int(speculative_k))
        self.lookup_ngram = max(1, int(lookup_ngram))
        self.capacity = int(max_seq_len or c.max_position_embeddings)
        if self.capacity > c.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.capacity} exceeds rope table "
                f"({c.max_position_embeddings})")
        self.chunk = int(chunk_size)
        self.top_k = int(top_k)
        self.stream_callback = stream_callback

        model.eval()
        _, params, _, buffers = collect_state(model)
        self._state = params + buffers
        self._state_vals = read_values(self._state)

        head_dim = c.hidden_size // c.num_attention_heads
        kvh = c.num_key_value_heads
        dt = model.llama.embed_tokens.weight.dtype
        L = c.num_hidden_layers
        # a prefill window is always a full `chunk` wide, so it must fit the
        # buffer (the final window slides BACK over already-written
        # positions instead of padding the time axis — see _admit)
        self.chunk = min(self.chunk, self.capacity)
        self._mesh = mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            def _zeros(shape, dtype):
                sharding = NamedSharding(mesh, PartitionSpec())
                shard = np.zeros(sharding.shard_shape(tuple(shape)), dtype)
                return jax.make_array_from_callback(
                    shape, sharding, lambda idx: shard)
        else:
            _zeros = jnp.zeros
        import ml_dtypes  # noqa: F401  (np.zeros understands bf16 via jnp)
        np_dt = np.dtype(dt) if mesh is not None else dt
        shape = (self.B, self.capacity, kvh, head_dim)
        self._k = [_zeros(shape, np_dt) for _ in range(L)]
        self._v = [_zeros(shape, np_dt) for _ in range(L)]
        self._logits = _zeros((self.B, c.vocab_size), np.float32
                              if mesh is not None else jnp.float32)
        self._lens = _zeros((self.B,), np.int32
                            if mesh is not None else jnp.int32)
        # device-side committed-token history (speculative mode): the
        # in-graph prompt-lookup draft reads it, decode windows append
        self._tokens = _zeros((self.B, self.capacity), np.int32
                              if mesh is not None else jnp.int32) \
            if self.speculative_k > 1 else None
        self._n_layers = L

        # host-side slot table / queues
        self.slots: list[_Slot | None] = [None] * self.B
        self.waiting: collections.deque[GenerationRequest] = \
            collections.deque()
        self.finished_outputs: dict[int, RequestOutput] = {}
        self._next_id = 0
        self._rng_key = None
        self._step_fn = None
        self._prefill_fn = None
        self._set_logits_fn = None
        self.stats = {"steps": 0, "prefill_chunks": 0, "tokens_generated": 0,
                      "draft_tokens_accepted": 0, "decode_time_s": 0.0}

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _programs(self):
        if self._step_fn is not None:
            return
        model = self.model
        state = self._state
        B, cap, chunk = self.B, self.capacity, self.chunk
        top_k = self.top_k

        K = self.horizon

        def one_step(k_bufs, v_bufs, logits, lens, active, rng, state_vals,
                     temps, top_ps, eos_ids):
            """sample from current logits -> one-token model step."""
            rng, sub = jax.random.split(rng)
            greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = _sample_logits_device(
                logits, sub, jnp.maximum(temps, 1e-6)[:, None], top_k,
                top_ps[:, None], False, True)
            nxt = jnp.where(temps <= 0.0, greedy_tok, sampled)
            # inactive slots decode garbage; pin them to token 0
            nxt = jnp.where(active, nxt, 0)
            with functional_mode(), _bind(state, state_vals):
                caches = [SlotKVCache(k, v, lens)
                          for k, v in zip(k_bufs, v_bufs)]
                hidden, new_caches = model.llama(
                    Tensor(nxt[:, None]), kv_caches=caches,
                    position_offset=Tensor(lens))
                new_logits = model._logits(hidden)._value[:, 0] \
                    .astype(jnp.float32)
            kb = [cc.k._value if isinstance(cc.k, Tensor) else cc.k
                  for cc in new_caches]
            vb = [cc.v._value if isinstance(cc.v, Tensor) else cc.v
                  for cc in new_caches]
            new_lens = jnp.where(active, lens + 1, lens)
            finished = active & (nxt == eos_ids)
            return nxt, new_logits, kb, vb, new_lens, finished, rng

        def step(state_vals, k_bufs, v_bufs, logits, lens, active, rng,
                 temps, top_ps, eos_ids, budgets):
            """`horizon` decode iterations as ONE compiled lax.scan — the
            host sync (and through a tunnel, the RTT) amortizes over K
            tokens per slot. A slot that hits eos, capacity, or its
            remaining budget mid-horizon deactivates in-graph; the host
            reads the per-iteration (tokens, active) history to attribute
            outputs."""
            def body(carry, _):
                kb, vb, logits, lens, act, emitted, rng = carry
                nxt, logits, kb, vb, lens, finished, rng = one_step(
                    kb, vb, logits, lens, act, rng, state_vals, temps,
                    top_ps, eos_ids)
                emitted = emitted + act.astype(jnp.int32)
                act_next = act & ~finished & (lens < cap - 1) & \
                    (emitted < budgets)
                return (kb, vb, logits, lens, act_next, emitted, rng), \
                    (nxt, act)

            emitted0 = jnp.zeros_like(lens)
            (k_bufs, v_bufs, logits, lens, active, _, rng), \
                (toks, was_active) = jax.lax.scan(
                    body,
                    (k_bufs, v_bufs, logits, lens, active, emitted0, rng),
                    None, length=K)
            return toks, was_active, logits, k_bufs, v_bufs, lens, rng

        Kspec = self.speculative_k
        ngram = self.lookup_ngram

        def spec_step(state_vals, k_bufs, v_bufs, logits, lens, active, rng,
                      temps, top_ps, eos_ids, budgets, tokens_buf):
            """`horizon` speculative verify windows as ONE compiled scan.
            Each window: in-graph prompt-lookup draft from the device token
            history -> commit one sampled token + verify the Kspec-1 drafts
            with ONE Kspec-token model call (_spec_accept: greedy rows
            token-exact, sampled rows rejection-sampling). KV written past
            the accepted prefix is stale but unreferenced (lens-based
            masks) and is overwritten by the next window."""
            def body(carry, _):
                kb, vb, logits, lens, act, emitted, rng, tbuf = carry
                draft = _lookup_draft(tbuf, lens, Kspec - 1, ngram)
                rng, sub, sub2 = jax.random.split(rng, 3)
                greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                sampled = _sample_logits_device(
                    logits, sub, jnp.maximum(temps, 1e-6)[:, None], top_k,
                    top_ps[:, None], False, True)
                committed = jnp.where(temps <= 0.0, greedy_tok, sampled)
                committed = jnp.where(act, committed, 0)
                window = jnp.concatenate([committed[:, None], draft],
                                         axis=1)
                with functional_mode(), _bind(state, state_vals):
                    caches = [SlotKVCache(k, v, lens)
                              for k, v in zip(kb, vb)]
                    hidden, new_caches = model.llama(
                        Tensor(window), kv_caches=caches,
                        position_offset=Tensor(lens))
                    logits_all = model._logits(hidden)._value \
                        .astype(jnp.float32)                # [B, K, V]
                kb = [cc.k._value if isinstance(cc.k, Tensor) else cc.k
                      for cc in new_caches]
                vb = [cc.v._value if isinstance(cc.v, Tensor) else cc.v
                      for cc in new_caches]
                n_acc, new_logits = _spec_accept(
                    logits_all, draft, temps, top_ps, top_k, act, sub2)
                counts = jnp.where(act, 1 + n_acc, 0)
                new_lens = lens + counts
                tbuf = _write_window(tbuf, window, lens)
                emitted = emitted + counts
                kidx = jnp.arange(Kspec)[None, :]
                in_window = kidx < counts[:, None]
                eos_hit = jnp.any(
                    in_window & (window == eos_ids[:, None]), axis=1)
                act_next = act & ~eos_hit & \
                    (new_lens < cap - Kspec) & (emitted < budgets)
                return (kb, vb, new_logits, new_lens, act_next, emitted,
                        rng, tbuf), (window, counts, act)

            emitted0 = jnp.zeros_like(lens)
            (k_bufs, v_bufs, logits, lens, active, _, rng, tokens_buf), \
                (toks, counts, was_active) = jax.lax.scan(
                    body,
                    (k_bufs, v_bufs, logits, lens, active, emitted0, rng,
                     tokens_buf),
                    None, length=K)
            return (toks, counts, was_active, logits, k_bufs, v_bufs, lens,
                    rng, tokens_buf)

        def prefill_chunk(state_vals, k_bufs, v_bufs, ids, slot, off, last):
            """Run chunk `ids` [1, chunk] of one prompt through the model
            against slot `slot`'s KV region starting at position `off`;
            returns updated buffers + the logits at in-chunk row `last`."""
            from ..models.llama import StaticKVCache

            z = jnp.int32(0)
            k_slot = [jax.lax.dynamic_slice(
                k, (slot, z, z, z), (1,) + k.shape[1:]) for k in k_bufs]
            v_slot = [jax.lax.dynamic_slice(
                v, (slot, z, z, z), (1,) + v.shape[1:]) for v in v_bufs]
            with functional_mode(), _bind(state, state_vals):
                caches = [StaticKVCache(k, v)
                          for k, v in zip(k_slot, v_slot)]
                hidden, new_caches = model.llama(
                    Tensor(ids), kv_caches=caches,
                    position_offset=Tensor(off))
                row = jax.lax.dynamic_slice(
                    hidden._value, (z, last, z), (1, 1, hidden.shape[-1]))
                logits_row = model._logits(Tensor(row))._value[0, 0] \
                    .astype(jnp.float32)
            k_out = [jax.lax.dynamic_update_slice(
                kb, (cc.k._value if isinstance(cc.k, Tensor) else cc.k
                     ).astype(kb.dtype), (slot, z, z, z))
                for kb, cc in zip(k_bufs, new_caches)]
            v_out = [jax.lax.dynamic_update_slice(
                vb, (cc.v._value if isinstance(cc.v, Tensor) else cc.v
                     ).astype(vb.dtype), (slot, z, z, z))
                for vb, cc in zip(v_bufs, new_caches)]
            return k_out, v_out, logits_row

        def set_logits(logits, row, slot):
            return jax.lax.dynamic_update_slice(
                logits, row[None].astype(logits.dtype), (slot, jnp.int32(0)))

        def set_tokens(tokens_buf, row, slot):
            return jax.lax.dynamic_update_slice(
                tokens_buf, row[None].astype(jnp.int32),
                (slot, jnp.int32(0)))

        def set_len(lens, slot, val):
            return jax.lax.dynamic_update_slice(lens, val[None], (slot,))

        self._step_fn = jax.jit(step, donate_argnums=(1, 2, 3))
        self._spec_fn = jax.jit(spec_step, donate_argnums=(1, 2, 3, 11))
        self._prefill_fn = jax.jit(prefill_chunk, donate_argnums=(1, 2))
        self._set_logits_fn = jax.jit(set_logits, donate_argnums=(0,))
        self._set_tokens_fn = jax.jit(set_tokens, donate_argnums=(0,))
        self._set_len_fn = jax.jit(set_len, donate_argnums=(0,))

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=64, temperature=0.0,
                    top_p=1.0, eos_token_id=None, request_id=None):
        ids = np.asarray(
            prompt_ids.numpy() if hasattr(prompt_ids, "numpy")
            else prompt_ids, dtype=np.int32).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if len(ids) >= self.capacity - self.speculative_k:
            raise ValueError(f"prompt of {len(ids)} tokens leaves no room "
                             f"to generate (engine capacity "
                             f"{self.capacity})")
        rid = self._next_id if request_id is None else request_id
        if request_id is not None and (
                rid in self.finished_outputs
                or any(r.request_id == rid for r in self.waiting)
                or any(s is not None and s.req.request_id == rid
                       for s in self.slots)):
            raise ValueError(f"duplicate request_id {rid!r}")
        self._next_id = max(self._next_id, rid) + 1
        self.waiting.append(GenerationRequest(
            rid, ids, int(max_new_tokens), float(temperature), float(top_p),
            eos_token_id))
        return rid

    def has_unfinished(self):
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def cancel(self, request_id):
        """Cancel a waiting or running request. Returns the partial
        RequestOutput (finish_reason 'cancelled'), or None if the id is
        unknown/already finished. A cancelled running slot frees at the
        next step boundary (its KV region is simply reused)."""
        for i, req in enumerate(self.waiting):
            if req.request_id == request_id:
                del self.waiting[i]
                out = RequestOutput(request_id, [], True, "cancelled")
                self.finished_outputs[request_id] = out
                return out
        for b, slot in enumerate(self.slots):
            if slot is not None and slot.req.request_id == request_id:
                out = RequestOutput(request_id, list(slot.generated), True,
                                    "cancelled")
                self.finished_outputs[request_id] = out
                self.slots[b] = None
                return out
        return None

    def _admit(self, slot_idx, req):
        """Chunked prefill of `req` into slot `slot_idx`."""
        self._programs()
        P = len(req.prompt_ids)
        off = 0
        logits_row = None
        while off < P:
            take = min(self.chunk, P - off)
            # JAX dynamic slices CLAMP out-of-range starts, so a window that
            # would cross the buffer end slides BACK instead: positions
            # [win, off) are recomputed (producing identical KV) and the new
            # tokens land exactly at [off, off+take)
            win = min(off, self.capacity - self.chunk)
            chunk_ids = np.zeros((1, self.chunk), np.int32)
            real = req.prompt_ids[win:min(win + self.chunk, P)]
            chunk_ids[0, :len(real)] = real
            self._k, self._v, logits_row = self._prefill_fn(
                self._state_vals, self._k, self._v, chunk_ids,
                np.int32(slot_idx), np.int32(win),
                np.int32(off + take - 1 - win))
            off += take
            self.stats["prefill_chunks"] += 1
        self._logits = self._set_logits_fn(self._logits, logits_row,
                                           np.int32(slot_idx))
        self._lens = self._set_len_fn(self._lens, np.int32(slot_idx),
                                      np.int32(P))
        if self._tokens is not None:
            # token history for in-graph drafting: the prompt, zero-padded
            row = np.zeros((self.capacity,), np.int32)
            row[:P] = req.prompt_ids
            self._tokens = self._set_tokens_fn(
                self._tokens, row, np.int32(slot_idx))
        self.slots[slot_idx] = _Slot(req, P)

    def _admit_waiting(self):
        for b in range(self.B):
            if not self.waiting:
                break
            if self.slots[b] is None:
                req = self.waiting[0]
                room = self.capacity - len(req.prompt_ids) - \
                    self.speculative_k
                if req.max_new_tokens > room:
                    import warnings
                    warnings.warn(
                        f"request {req.request_id}: capping max_new_tokens "
                        f"{req.max_new_tokens} -> {room} (engine capacity "
                        f"{self.capacity})", RuntimeWarning, stacklevel=3)
                    req.max_new_tokens = room
                self.waiting.popleft()
                self._admit(b, req)

    # ------------------------------------------------------------------
    # the engine loop
    # ------------------------------------------------------------------
    def step(self):
        """Admit waiting requests into free slots, run ONE decode step for
        all active slots, retire finished requests. Returns the list of
        RequestOutput finished by this step."""
        from ..core import random as _random

        self._admit_waiting()
        if not any(s is not None for s in self.slots):
            return []
        self._programs()
        if self._rng_key is None:
            seed, counter = _random.default_generator.next_seed()
            key = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
            if self._mesh is not None:
                # multi-process: the key must be a GLOBAL replicated array
                # (every process derives the identical value from the seed)
                from jax.sharding import NamedSharding, PartitionSpec
                data = np.asarray(jax.random.key_data(key))
                glob = jax.make_array_from_callback(
                    data.shape,
                    NamedSharding(self._mesh, PartitionSpec()),
                    lambda idx: data[idx])
                key = jax.random.wrap_key_data(glob)
            self._rng_key = key
        active = np.array([s is not None for s in self.slots])
        temps = np.array([s.req.temperature if s else 0.0
                          for s in self.slots], np.float32)
        top_ps = np.array([s.req.top_p if s else 1.0
                           for s in self.slots], np.float32)
        eos_ids = np.array([(s.req.eos_token_id if s and
                             s.req.eos_token_id is not None else -1)
                            for s in self.slots], np.int32)
        budgets = np.array([(s.req.max_new_tokens - len(s.generated))
                            if s else 0 for s in self.slots], np.int32)

        t0 = time.perf_counter()
        spec = self.speculative_k > 1
        if spec:
            (toks, counts, was_active, self._logits, self._k, self._v,
             self._lens, self._rng_key, self._tokens) = self._spec_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, active, self._rng_key,
                temps, top_ps, eos_ids, budgets, self._tokens)
            toks3 = np.asarray(toks)          # [Kh, B, Kspec]
            counts_np = np.asarray(counts)    # [Kh, B]
            wa_np = np.asarray(was_active)    # [Kh, B]
            Kh, B_, Ks = toks3.shape
            # flatten windows into the [rows, B] stream the readout walks;
            # a window row i is live for slot b iff i < counts (acceptance
            # truncates windows, so the stream has per-window gaps — the
            # readout SKIPS dead rows instead of stopping at them)
            toks_np = toks3.transpose(0, 2, 1).reshape(Kh * Ks, B_)
            act_np = ((np.arange(Ks)[None, :, None] <
                       counts_np[:, None, :]) &
                      wa_np[:, None, :]).reshape(Kh * Ks, B_)
        else:
            (toks, was_active, self._logits, self._k, self._v, self._lens,
             self._rng_key) = self._step_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, active, self._rng_key,
                temps, top_ps, eos_ids, budgets)
            toks_np = np.asarray(toks)       # [K, B] — the per-step transfer
            act_np = np.asarray(was_active)  # [K, B]
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self.stats["steps"] += 1

        done = []
        for b, slot in enumerate(self.slots):
            if slot is None:
                continue
            finish_reason = None
            n_read = 0
            for k in range(toks_np.shape[0]):
                if not act_np[k, b]:
                    if spec:
                        # rejected tail of a verify window: later windows
                        # may still hold live tokens
                        continue
                    # deactivated in-graph before this iteration (eos or
                    # capacity hit at an earlier k): nothing more to read
                    break
                tok = int(toks_np[k, b])
                slot.generated.append(tok)
                n_read += 1
                self.stats["tokens_generated"] += 1
                if self.stream_callback is not None:
                    self.stream_callback(slot.req.request_id, tok)
                    if self.slots[b] is not slot:
                        # the callback cancelled this request re-entrantly;
                        # stop reading its window and keep the 'cancelled'
                        # output it recorded
                        break
                if slot.req.eos_token_id is not None and \
                        tok == slot.req.eos_token_id:
                    finish_reason = "eos"
                elif len(slot.generated) >= slot.req.max_new_tokens:
                    finish_reason = "length"
                elif slot.prompt_len + len(slot.generated) >= \
                        self.capacity - self.speculative_k:
                    # margin of K: a verify window writes K positions, and
                    # JAX dynamic updates would clamp past the buffer end
                    finish_reason = "capacity"
                if finish_reason:
                    break
            if spec and n_read > 0:
                # drafts that actually landed in an output (row 0 of each
                # window is the committed sample, not a draft)
                Ks = self.speculative_k
                n_committed = sum(
                    1 for k in range(toks_np.shape[0])
                    if act_np[k, b] and k % Ks == 0)
                self.stats["draft_tokens_accepted"] += max(
                    n_read - n_committed, 0)
            if self.slots[b] is not slot:
                continue  # cancelled mid-window; don't record a finish
            if finish_reason:
                out = RequestOutput(slot.req.request_id,
                                    list(slot.generated), True,
                                    finish_reason)
                self.finished_outputs[slot.req.request_id] = out
                done.append(out)
                self.slots[b] = None  # slot freed; next step admits into it
        return done

    def generate(self, prompts, **sampling):
        """Drain-mode convenience: submit all prompts, run steps until every
        request finishes, return outputs in submission order. Pops its
        outputs from `finished_outputs` — long-running step()-driven servers
        should likewise consume step()'s return list and delete (or pop)
        entries they read, or the dict grows without bound."""
        rids = [self.add_request(p, **sampling) for p in prompts]
        while self.has_unfinished():
            self.step()
        return [self.finished_outputs.pop(r) for r in rids]

    def throughput(self):
        dt = self.stats["decode_time_s"]
        return self.stats["tokens_generated"] / dt if dt > 0 else 0.0

    def reset_stats(self):
        for key in self.stats:
            self.stats[key] = 0.0 if key.endswith("_s") else 0


def _bind(state, values):
    from ..jit.functional_call import bind_state
    return bind_state(state, values)


def _lookup_draft(tokens_buf, lens, k_draft, ngram):
    """In-graph prompt-lookup drafting: for each row, match the committed
    history's final `ngram` tokens against the history itself (most recent
    match wins) and propose the `k_draft` tokens that followed it. Falls
    back to repeating the last token — a bad draft only wastes the verify
    window, never changes output."""
    cap = tokens_buf.shape[1]
    idx = jnp.arange(cap)

    def per_row(buf, L):
        tail_start = jnp.maximum(L - ngram, 0)
        tail = jax.lax.dynamic_slice(buf, (tail_start,), (ngram,))
        eq = jnp.ones((cap,), bool)
        for j in range(ngram):
            # buf[i + j] == tail[j] for every window position i
            eq = eq & (jnp.roll(buf, -j) == tail[j])
        m = eq & (idx < (L - ngram))  # exclude the tail's own position
        has = jnp.any(m)
        i_star = cap - 1 - jnp.argmax(jnp.flip(m))  # most recent match
        start = jnp.where(has, i_star + ngram, 0)
        cont = jax.lax.dynamic_slice(buf, (start,), (k_draft,))
        last = buf[jnp.maximum(L - 1, 0)]
        pos = start + jnp.arange(k_draft)
        return jnp.where(has & (pos < L), cont, last).astype(jnp.int32)

    return jax.vmap(per_row)(tokens_buf, lens.astype(jnp.int32))


def _write_window(tokens_buf, window, lens):
    """Append a verify window's tokens to each row's history at its own
    length (rejected-tail positions are overwritten by later windows)."""
    def per_row(buf, w, L):
        return jax.lax.dynamic_update_slice(buf, w, (L,))

    return jax.vmap(per_row)(tokens_buf, window.astype(jnp.int32),
                             lens.astype(jnp.int32))


def _processed_probs(logits, temps, top_ps, top_k):
    """The temperature/top-k/top-p filtered distribution the engine samples
    from, as probabilities — delegates to the ONE shared filter pipeline
    (models.llama._filter_logits) so the rejection-sampling acceptance can
    never drift from the sampler."""
    from ..models.llama import _filter_logits
    filtered = _filter_logits(
        logits, jnp.maximum(temps, 1e-6)[:, None, None],
        top_k, top_ps[:, None, None])
    return jax.nn.softmax(filtered, axis=-1)


def _spec_accept(logits_all, draft, temps, top_ps, top_k, active, key):
    """Acceptance rule for one verify window. ``logits_all`` [B, K, V] are
    the model's logits over the window; ``draft`` [B, K-1] the proposals.

    Greedy rows (temp<=0): draft i survives iff it equals the model's
    argmax prediction and every earlier draft did — output is token-exact
    vs step-by-step decode.

    Sampled rows: REJECTION SAMPLING against the processed target
    distribution p: the prompt-lookup proposal is a delta at the drafted
    token, so draft d is accepted with probability min(1, p(d)); on the
    first rejection, the returned next-step logits mask d out, so the next
    committed sample comes from the residual norm((p - delta_d)+). For
    pure temperature sampling this makes the output distribution EXACTLY p
    per position; with top-k/top-p the next step re-filters the masked
    logits, which can shift the nucleus boundary by one token (documented
    approximation).

    Returns (n_acc [B], next_logits [B, V])."""
    B, K, V = logits_all.shape
    probs = _processed_probs(logits_all[:, :-1], temps, top_ps, top_k)
    p_draft = jnp.take_along_axis(probs, draft[..., None],
                                  axis=-1)[..., 0]          # [B, K-1]
    u = jax.random.uniform(key, draft.shape)
    greedy_next = jnp.argmax(logits_all[:, :-1], axis=-1).astype(jnp.int32)
    is_greedy = (temps <= 0.0)[:, None]
    acc = jnp.where(is_greedy, greedy_next == draft, u < p_draft)
    acc = acc & active[:, None]
    accum = jnp.cumprod(acc.astype(jnp.int32), axis=1)
    n_acc = accum.sum(axis=1).astype(jnp.int32)
    next_logits = jnp.take_along_axis(
        logits_all, n_acc[:, None, None], axis=1)[:, 0]
    rejected = (temps > 0.0) & (n_acc < K - 1) & active
    rej_tok = jnp.take_along_axis(
        draft, jnp.clip(n_acc, 0, K - 2)[:, None], axis=1)[:, 0]
    hit = jax.nn.one_hot(rej_tok, V, dtype=bool)
    next_logits = jnp.where(rejected[:, None] & hit, -1e30, next_logits)
    return n_acc, next_logits
