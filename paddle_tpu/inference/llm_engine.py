"""LLM serving engine — continuous batching over compiled decode steps.

Reference analog: the serving path the reference builds from
AnalysisPredictor (paddle/fluid/inference/api/analysis_predictor.h:101) plus
the fused decode kernels
(python/paddle/incubate/nn/functional/block_multihead_attention.py:1,
masked_multihead_attention.py:1) that PaddleNLP's serving stack drives with
dynamic request batching.

TPU-native design — everything is STATIC shapes so two compiled programs
serve the whole engine lifetime:

  * ``max_batch`` fixed slots; each slot owns a [capacity, H, D] region of
    the per-layer KV buffers and a traced length (``SlotKVCache``), so
    ragged sequences share one compiled decode step.
  * one **decode step** program: sample (per-slot temperature/top-p vectors,
    greedy-vs-sample selected per slot in-graph) -> one-token model step
    writing KV at each slot's own position -> next logits. Varying sampling
    params or slot occupancy never recompiles.
  * one **chunked-prefill** program per chunk size: admits a request by
    streaming its prompt through fixed-size chunks into its slot's KV region
    (dynamic_slice/update on the slot axis), returning last-position logits.
    Chunk padding is masked by causality and overwritten by later writes.
  * requests join and leave BETWEEN steps (continuous batching): a finished
    slot is freed at the step boundary and the next queued request admits
    into it while other slots keep decoding.

Logits stay on device between steps; the only per-step host transfer is the
[B] sampled-token vector that streaming callers need anyway.
"""
from __future__ import annotations

import collections
import dataclasses
import hashlib
import heapq
import os
import threading
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import lock_watchdog as _lockwatch
from ..core.tensor import Tensor, functional_mode
from ..models.llama import SlotKVCache, _sample_logits_device
from ..models.lora import lora_scope

__all__ = ["LLMEngine", "GenerationRequest", "RequestOutput", "PendingStep",
           "PoolCapacityError", "default_engine_stats"]


def default_engine_stats():
    """Fresh engine ``stats`` dict — THE one copy of the key schema.
    The serving layer reads these keys by name off ANY engine speaking
    the step protocol (LLMEngine, and protocol shims like
    serving/embedding.py's BertEmbedEngine), so every engine must carry
    the full set: a hand-copied dict would silently drift the next time
    a counter is added."""
    return {"steps": 0, "prefill_chunks": 0, "tokens_generated": 0,
            "draft_tokens_accepted": 0,
            "spec_proposed_tokens": 0, "spec_accepted_tokens": 0,
            "preemptions": 0,
            "fused_steps": 0, "multi_steps": 0,
            "prefill_tokens": 0,
            "prefix_hit_tokens": 0, "prefix_cow_blocks": 0,
            "prefix_evicted_blocks": 0,
            "adapter_cache_hits": 0, "adapter_cache_misses": 0,
            "adapter_swaps": 0, "embed_requests": 0,
            # host KV tier: preemption swap (blocks/bytes each way, and
            # re-prefill tokens the restore avoided) + prefix spill
            # (LRU-evicted blocks demoted to host, spilled blocks
            # promoted back on a content-store hit)
            "kv_swap_out_blocks": 0, "kv_swap_in_blocks": 0,
            "kv_swap_out_bytes": 0, "kv_swap_in_bytes": 0,
            "kv_swap_saved_tokens": 0,
            "kv_spill_blocks": 0, "kv_promote_blocks": 0,
            # cross-replica KV shipping (disaggregated prefill/decode):
            # staged-entry exports out of this engine's pool and shipped
            # imports scattered back in — booked SEPARATELY from the
            # kv_swap_* preemption traffic, whose byte deltas are the
            # explain_tail preempt classifier's exclusive signal
            "kv_ship_out_blocks": 0, "kv_ship_in_blocks": 0,
            "kv_ship_out_bytes": 0, "kv_ship_in_bytes": 0,
            "swap_out_time_s": 0.0, "swap_in_time_s": 0.0,
            "decode_time_s": 0.0, "admit_time_s": 0.0,
            "dispatch_time_s": 0.0, "host_sync_time_s": 0.0,
            "emit_time_s": 0.0,
            # transfer-guard sanitizer (PADDLE_TPU_TRANSFER_CHECKS=1):
            # all-decode strides whose dispatch->readout window ran
            # under jax.transfer_guard("disallow") — each counted
            # readout is the stride's ONE permitted D2H sync
            "guarded_syncs": 0}

#: chain-hash seed for block 0 of every sequence (the "parent" of the
#: first block) — a fixed constant so equal first blocks collide
_ROOT_HASH = b"paddle-tpu-prefix-root"

#: smoothing of the per-request draft-acceptance EWMA that drives the
#: acceptance-adaptive verify-k grants (fused speculative scheduling):
#: high enough that a request whose drafts stop accepting sheds its
#: window within a few readouts, low enough that one unlucky window
#: doesn't collapse k for a stream that usually accepts
_SPEC_EWMA_ALPHA = 0.4

#: one RLock per MODEL object, shared by every engine built on it. The
#: compiled programs trace through ``bind_state``, which temporarily
#: swaps the model tensors' ``_value`` to tracers — so two engines on
#: the SAME model tracing from different threads (N replica servers of a
#: ReplicaRouter sharing weights) would leak each other's tracers.
#: step_begin (the only trace-capable engine entry point) serializes on
#: this lock; once every program is compiled the lock guards only the
#: sub-ms host-side dispatch, which the GIL serializes anyway.
_MODEL_DISPATCH_LOCKS = weakref.WeakKeyDictionary()
_LOCKS_GUARD = threading.Lock()

#: the open transfer-guard stride window, PER THREAD and shared by ALL
#: engines — jax.transfer_guard is thread-global config, so two engines
#: interleaved on one thread must share one window slot (per-engine
#: slots would nest contexts and unwind them out of LIFO order,
#: stranding the thread in "disallow")
_STRIDE_GUARD_TLS = threading.local()


def close_thread_stride_guard(finishing=None):
    """Close the CALLING thread's open transfer-guard stride window, if
    any — THE one copy of the close protocol, shared by every engine
    speaking the step protocol (LLMEngine, and shims like
    serving/embedding.py's BertEmbedEngine, whose readouts must not run
    inside another engine's disallow window). A window closed early —
    by a chained dispatch, a reset, or a DIFFERENT pending's finish —
    did not cover its stride, so the owner's ``guarded`` flag is
    revoked and its readout is not counted."""
    cm = getattr(_STRIDE_GUARD_TLS, "cm", None)
    if cm is None:
        return
    owner = getattr(_STRIDE_GUARD_TLS, "owner", None)
    if owner is not None and owner is not finishing:
        owner.guarded = False
    _STRIDE_GUARD_TLS.cm = None
    _STRIDE_GUARD_TLS.owner = None
    cm.__exit__(None, None, None)


def _model_dispatch_lock(model):
    with _LOCKS_GUARD:
        lock = _MODEL_DISPATCH_LOCKS.get(model)
        if lock is None:
            lock = _MODEL_DISPATCH_LOCKS[model] = threading.RLock()
        return lock


class PoolCapacityError(RuntimeError):
    """The head waiting request's prompt cannot prefill into the paged
    pool at all (kv_pool_blocks too small). A RuntimeError subclass so
    existing callers keep working; the serving layer catches exactly this
    type to reject the one doomed request instead of treating unrelated
    runtime errors (device/compile failures) as per-request problems."""


@dataclasses.dataclass
class GenerationRequest:
    request_id: int
    prompt_ids: np.ndarray           # [P] int32
    max_new_tokens: int = 64
    temperature: float = 0.0         # <=0 -> greedy
    top_p: float = 1.0
    eos_token_id: int | None = None
    #: latency-tier pin: cap the multi-step readout stride of every
    #: all-decode step this request is active in (None = the engine's
    #: ``readout_stride``; 1 = every step syncs the host, minimizing
    #: inter-token latency for THIS request at the whole batch's
    #: throughput cost — the effective stride is the min over slots)
    readout_stride: int | None = None
    #: the TENANT dimension (batched multi-LoRA,
    #: serving/adapters.py): 0 = the base model, > 0 = a registered
    #: adapter whose gathered low-rank delta rides this request's rows
    #: of every fused dispatch. Carried through preemption re-prefill,
    #: supervised-restart re-admission and router failover, and mixed
    #: into the prefix cache's hash-chain root so tenants never share
    #: KV blocks.
    adapter_id: int = 0
    #: the request's GRANT KIND in the fused token-budget walk:
    #: "generate" (prefill chunks, then one decode token per step) or
    #: "embed" (PREFILL-ONLY — no decode tokens, no sampling; the
    #: mean-pooled final hidden state returns on the prefill sync)
    kind: str = "generate"
    #: acceptance-adaptive speculation state (fused verify-k grants):
    #: EWMA of accepted/proposed drafts for THIS request, None until the
    #: first verify readout. Carried through preemption re-prefill and
    #: supervised-restart re-admission (the engine's rid-keyed
    #: ``_spec_ewma`` mirror survives ``reset()``) like the PR-8
    #: ``readout_stride`` pins, so a low-acceptance request does not
    #: reset to full-window speculation every time it moves.
    spec_ewma: float | None = None
    #: disaggregated serving (cross-replica KV shipping): stage this
    #: request's committed KV as an export entry when it finishes — the
    #: prefill replica's router hook then pops it via
    #: :meth:`LLMEngine.export_kv` and ships it to a decode replica.
    #: The staging runs at the finish site on the ENGINE thread, while
    #: the slot's blocks are still allocated (an external export call
    #: would race the retirement free).
    export_kv: bool = False
    #: distributed trace context (serving/types.TraceContext or its
    #: dict form) — opaque to the engine except for the recorder stamp
    #: at admission; preserved verbatim so one trace_id names this
    #: request across every replica/restart hop it takes
    trace_ctx: object | None = None


@dataclasses.dataclass
class RequestOutput:
    request_id: int
    token_ids: list
    finished: bool = False
    finish_reason: str | None = None
    #: prefill-only (kind="embed") result: the mean-pooled final hidden
    #: state [hidden_size] (fp32), None for generation requests
    embedding: np.ndarray | None = None


class _Slot:
    __slots__ = ("req", "generated", "prompt_len", "prefill_pos", "inflight",
                 "chain", "reg_blocks", "a_slot")

    def __init__(self, req, prompt_len, prefill_pos=None):
        self.req = req
        self.generated = []
        self.prompt_len = prompt_len
        #: device ROW of this request's adapter in the AdapterDeviceCache
        #: stacks (0 = the all-zeros base row) — the per-slot index the
        #: fused step gathers the LoRA delta by
        self.a_slot = 0
        #: prefix-cache chain state (paged + enable_prefix_cache): the
        #: rolling chain hash of each REGISTERED full block of this
        #: slot's committed token stream, and how many blocks have been
        #: registered in the content store so far. Admission seeds both
        #: from the probe's hit; prefill/decode extend them as blocks
        #: fill.
        self.chain = []
        self.reg_blocks = 0
        #: prompt tokens whose prefill has been DISPATCHED (== prompt_len
        #: once ramp-in completes; legacy admission prefills everything up
        #: front). The fused scheduler advances it one chunk grant at a
        #: time, so a partially-prefilled request stays RESIDENT in its
        #: slot between steps instead of blocking inside _admit.
        self.prefill_pos = prompt_len if prefill_pos is None else prefill_pos
        #: decode tokens dispatched but not yet step_finish()ed — the
        #: paged fused engine's host-side lens mirror (scheduled growth),
        #: what lets it allocate blocks for step N+1 before step N's
        #: readout and so pipeline at depth 2 on a full pool.
        self.inflight = 0

    @property
    def ramping(self):
        return self.prefill_pos < self.prompt_len

    def sched_len(self):
        """Scheduled sequence length: what the device lens will be once
        every dispatched step lands (== current length when nothing is in
        flight)."""
        return self.prefill_pos + len(self.generated) + self.inflight


class PendingStep:
    """One in-flight decode step: device-array futures dispatched by
    :meth:`LLMEngine.step_begin`, host readout deferred to
    :meth:`LLMEngine.step_finish`.

    This split is what makes PIPELINED serving (``paddle_tpu.serving``)
    possible: a second ``step_begin()`` may be dispatched before the first
    ``step_finish()``, so JAX async dispatch overlaps step N+1's device
    compute with step N's device→host token transfer and host readout.
    ``slots`` snapshots the slot objects at dispatch time — a slot retired
    and reused between dispatch and finish fails the identity check at
    readout and its stale token column is dropped (it was decoded against
    the OLD request's state)."""

    __slots__ = ("toks", "was_active", "counts", "spec", "slots",
                 "pool_done", "sched", "step_id", "fenced", "t_dispatch",
                 "embed_done", "pooled", "verify", "offered", "guarded")

    def __init__(self, toks, was_active, counts, spec, slots, pool_done,
                 sched=None, fenced=None, embed_done=None, verify=None):
        self.toks = toks              # device [rows, B] (spec: [Kh,B,Ks])
        self.was_active = was_active  # device activity history
        self.counts = counts          # spec only: accepted counts [Kh, B]
        self.spec = spec
        self.slots = slots            # list[_Slot|None] snapshot at dispatch
        self.pool_done = pool_done    # outputs retired by the pool allocator
        #: fused scheduler: per-slot decode tokens SCHEDULED by this
        #: dispatch ({b: n}); step_finish pays them back off slot.inflight
        self.sched = sched or {}
        #: flight-recorder StepRecord id (None when no recorder is
        #: attached) — step_finish stamps every token it reads out with
        #: it, joining request timelines back to engine state
        self.step_id = None
        #: paged fused: physical blocks this dispatch may WRITE (the
        #: stride-aware in-flight fence) — step_finish drops the fence,
        #: releasing any block quarantined while this step was in flight
        self.fenced = fenced or []
        #: perf_counter at dispatch — step_finish amortizes per-token
        #: emit stamps over [t_dispatch, sync] so a k-step stride's
        #: token burst doesn't read as one giant inter-token gap
        self.t_dispatch = None
        #: [(slot_idx, _Slot), ...] embed requests whose FINAL prefill
        #: chunk this dispatch carries — step_finish reads their pooled
        #: hidden rows on the sync and retires them. ``pooled`` is THIS
        #: dispatch's pooled-accumulator output (not the engine's
        #: newest one: under pipelining the readout must not
        #: synchronize on younger in-flight steps).
        self.embed_done = embed_done or []
        self.pooled = None
        #: fused speculative dispatches: {slot: drafts granted} — the
        #: readout's acceptance accounting (EWMA + spec counters) and
        #: the paged BLOCK-TABLE ROLLBACK walk key off it
        self.verify = verify or {}
        #: fused speculative dispatches: device [windows, B] per-window
        #: OFFERED widths (1 + drafts after the in-graph clamps) — the
        #: exact proposal counts the acceptance accounting books
        #: against. None on legacy spec (its grant is never clamped).
        self.offered = None
        #: True when this dispatch armed the transfer-guard stride
        #: window (PADDLE_TPU_TRANSFER_CHECKS=1): its step_finish
        #: readout is the stride's ONE counted sync (stats
        #: ["guarded_syncs"])
        self.guarded = False


class LLMEngine:
    """Continuous-batching engine over a LlamaForCausalLM (works with
    bf16/fp32 and WeightOnlyLinear-quantized weights; under a mesh the
    programs partition by GSPMD like ``generate()``)."""

    def __init__(self, model, max_batch=4, max_seq_len=None, chunk_size=64,
                 top_k=0, stream_callback=None, horizon=1, speculative_k=1,
                 lookup_ngram=3, mesh=None, cache_impl="dense",
                 block_size=64, kv_pool_blocks=None, scheduler="legacy",
                 max_step_tokens=None, enable_prefix_cache=False,
                 readout_stride=1, adapter_store=None,
                 adapter_cache_slots=4, kv_cache_dtype=None,
                 kv_host_swap=False, kv_host_spill_bytes=0,
                 sampling_seed=None):
        """``scheduler="fused"`` (Sarathi-style chunked-prefill+decode
        fusion): admission becomes slot ASSIGNMENT only — each engine step
        then processes, per slot, either one bounded prefill chunk (for
        ramping-in requests, ``_Slot.prefill_pos`` tracks progress) or one
        decode token, all in ONE jitted mixed-step dispatch, under the
        per-step token budget ``max_step_tokens`` (default ``chunk_size +
        max_batch - 1``: one full chunk plus a decode token for every
        other slot; decode tokens are always granted — the budget bounds
        prefill interference, which is what stalls inter-token latency).
        Steps with no ramping slot fall through to the plain decode scan
        (with ``horizon``), so steady-state decode cost is unchanged.
        ``scheduler="legacy"`` keeps admit-then-decode: the whole prompt
        prefills inside _admit as a serial chunk train while running
        decodes stall — still the best shape for offline drain-mode
        batches, and the parity reference for the fused path.

        ``mesh``: a jax Mesh for MULTI-PROCESS serving — engine buffers
        are created as global (replicated) arrays on it so the compiled
        programs can mix them with TP-sharded weights whose groups span
        processes; every process runs the same step() calls (SPMD) and
        reads the same replicated token vector.

        ``cache_impl="paged"`` (reference:
        incubate/nn/functional/block_multihead_attention.py:1): KV lives in
        a physical BLOCK POOL of ``kv_pool_blocks`` blocks of ``block_size``
        tokens, mapped per slot through block tables. Blocks allocate on
        demand as sequences grow and free at retirement, so engine HBM is
        bounded by the POOL (sum of actual lengths, block-rounded), not by
        slots x capacity — and the pool may be OVERSUBSCRIBED
        (kv_pool_blocks < max_batch * capacity/block_size): when it runs
        dry mid-decode, the most recently admitted slot is PREEMPTED back
        to the waiting queue (its tokens re-prefill on re-admission, so
        greedy output is unchanged).

        ``enable_prefix_cache`` (paged only — vLLM/SGLang-style automatic
        prefix caching): the host block allocator becomes a ref-counted,
        CONTENT-ADDRESSED store. Full blocks are keyed by a rolling hash
        chained over the whole prefix (equal prefixes collide on
        purpose), blocks freed at retirement park in an LRU "cached" pool
        instead of the free list, and admission probes the store for the
        longest cached prefix — hit blocks attach by pure table writes +
        refcount bumps, so the shared span costs ZERO prefill FLOPs.
        Shared (refcounted) blocks are never written; a slot that must
        append into content another request still references gets a
        private COPY first (copy-on-write — the partial tail block is
        always private). Greedy output is token-exact vs the uncached
        engine; the LRU evicts before any live slot is preempted.

        ``kv_cache_dtype`` ("int8" | "int4", paged only — QUANTIZED KV
        serving, the capacity lever): the physical K/V pools store
        int8 (or int4 nibble-packed on the head dim) with one fp32
        scale per (physical block, kv head) riding alongside, so the
        same HBM holds ~2x/~4x the resident blocks. The Pallas
        decode/append kernels dequantize blocks in VMEM during the
        online-softmax walk and re-quantize every fused write in VMEM
        (fresh per-head absmax scale computed in-kernel); the CPU dense
        fallback does the same math at the XLA level, so tier-1 stays
        host-runnable. Everything ABOVE the pool — block tables,
        allocator, prefix-cache content hashing (host-side over token
        ids), COW, the write fence, speculative rollback — operates on
        block indices and is quantization-oblivious; scale arrays shard
        kv-heads under a TP mesh exactly like the pools. ``None`` (the
        default) is bit-identical to the bf16 engine. Output tokens
        DRIFT from bf16 (that is the deal: ~2x/4x capacity for a
        quantization error of ~0.4%/~7% per KV read); the serve bench's
        ``llama_serve_kv_quant`` A/B and tests/test_kv_quant.py track
        greedy drift explicitly.

        ``kv_host_swap`` (paged + fused only — the HOST KV TIER's
        preemption half): when pool pressure preempts a slot, its
        committed KV blocks are copied device→host asynchronously in
        the step_begin/step_finish gap instead of being discarded, and
        re-admission restores them host→device plus a one-token stitch
        — the preemption costs two overlapped copies, not a full
        re-prefill. Token-exact: the restored bytes are the bytes the
        pool held (quantized pools round-trip payload AND scale rows
        bit-exact), and the stitch position recomputes deterministically.

        ``kv_host_spill_bytes`` (paged + prefix cache only — the tier's
        eviction half): LRU-evicted prefix-cache blocks demote into a
        bounded host spill store of at most this many bytes instead of
        vanishing; a content-store probe that misses the device LRU but
        hits the spill PROMOTES the block back (one H2D copy) rather
        than recomputing the chunk. 0 (default) disables spilling.

        ``sampling_seed``: explicit base key for the per-(rid, position)
        fold_in sampling keys. The default (None) pulls a fresh seed
        from the global generator at the first step — fine for a single
        engine, but the generator's counter makes each engine's base key
        UNIQUE, so two replicas would sample different streams for the
        same rid. Disaggregated serving sets the SAME seed on every
        replica: a request migrated mid-stream (same rid, same
        positions) then re-samples token-exactly on the destination."""
        from ..jit.functional_call import collect_state, read_values

        self.model = model
        #: serializes trace-capable dispatches across ALL engines built
        #: on this model object (replica servers sharing weights) — see
        #: _model_dispatch_lock. Wrapped for the lock-order watchdog
        #: when PADDLE_TPU_LOCK_CHECKS=1 (paddle_tpu.analysis, PTL004).
        self._dispatch_lock = _lockwatch.tracked(
            _model_dispatch_lock(model), "LLMEngine._dispatch_lock")
        # ---- runtime sanitizers (paddle_tpu.analysis) -----------------
        #: PADDLE_TPU_TRANSFER_CHECKS=1 (the test conftest's posture):
        #: every fused all-decode stride holds jax.transfer_guard
        #: ("disallow") from dispatch to readout on the stepping thread,
        #: proving PR 8's one-sync-per-stride contract as an assertion —
        #: a stray scalar pull in the window raises instead of costing
        #: p99. The documented readout increments stats["guarded_syncs"].
        self._transfer_checks = os.environ.get(
            "PADDLE_TPU_TRANSFER_CHECKS", "0") not in ("", "0")
        #: PADDLE_TPU_LOCK_CHECKS=1: pin the paged-pool allocator to the
        #: stepping thread — any allocator/quarantine/content-store
        #: mutation from another thread raises, naming the owner (the
        #: dynamic half of the PTL004 lock-discipline pass)
        self._lock_checks = _lockwatch.enabled()
        self._pool_owner = None
        c = model.config
        self.B = int(max_batch)
        # decode horizon: tokens decoded per step() call as one compiled
        # lax.scan — amortizes the per-step host sync K-fold at the cost of
        # admitting/retiring requests only every K tokens
        self.horizon = max(1, int(horizon))
        # speculative verify windows (prompt-lookup drafting, NO reference
        # analog — the snapshot has no speculative decoding): each window
        # commits 1 sampled token plus up to speculative_k-1 drafted tokens
        # verified by ONE K-token model call. Drafting runs IN-GRAPH from a
        # device-side token history. Acceptance is COUPLED: a draft
        # survives iff it equals the token the engine would sample at
        # that position under its per-(rid, position) fold_in key, so a
        # speculative stream is TOKEN-IDENTICAL to the non-speculative
        # engine's — greedy and sampled alike — and restart/failover
        # resumption is exact in both modes. Under scheduler="legacy"
        # (dense only) windows run as a horizon scan; under
        # scheduler="fused" they are VERIFY grants in the token-budget
        # walk (any cache backend, mixing freely with prefill chunks,
        # plain decodes and embed prefills), with per-request
        # acceptance-adaptive draft counts and, for paged KV, zero-copy
        # block-table rollback of rejected tails.
        self.speculative_k = max(1, int(speculative_k))
        self.lookup_ngram = max(1, int(lookup_ngram))
        self.capacity = int(max_seq_len or c.max_position_embeddings)
        if self.capacity > c.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.capacity} exceeds rope table "
                f"({c.max_position_embeddings})")
        self.chunk = int(chunk_size)
        self.top_k = int(top_k)
        self.stream_callback = stream_callback
        if scheduler not in ("legacy", "fused"):
            raise ValueError(f"unknown scheduler {scheduler!r}")
        self.scheduler = scheduler
        #: multi-step on-device decode (fused scheduler): ALL-DECODE
        #: steps run up to ``readout_stride`` decode iterations as ONE
        #: compiled loop with IN-GRAPH early exit (every slot hit
        #: eos/budget/capacity -> the loop stops on device), so the host
        #: round-trip tax amortizes k-fold in steady state while mixed
        #: (ramp-in) steps keep per-step scheduling. A request may pin a
        #: smaller stride (latency tier) — the effective stride of a
        #: step is the MIN over its active slots' pins.
        self.readout_stride = max(1, int(readout_stride))
        if self.readout_stride > 1:
            if scheduler != "fused":
                raise ValueError(
                    "readout_stride > 1 needs scheduler='fused' (the "
                    "legacy scheduler already amortizes host syncs with "
                    "`horizon`; the stride is the fused scheduler's "
                    "all-decode fast path)")
            if self.horizon > 1:
                raise ValueError(
                    "readout_stride generalizes `horizon` for the fused "
                    "scheduler's all-decode steps — set one, not both")

        model.eval()
        _, params, _, buffers = collect_state(model)
        self._state = params + buffers
        self._state_vals = read_values(self._state)

        head_dim = c.hidden_size // c.num_attention_heads
        kvh = c.num_key_value_heads
        dt = model.llama.embed_tokens.weight.dtype
        L = c.num_hidden_layers
        # a prefill window is always a full `chunk` wide, so it must fit the
        # buffer (the final window slides BACK over already-written
        # positions instead of padding the time axis — see _admit)
        self.chunk = min(self.chunk, self.capacity)
        #: fused-scheduler per-step token cap: sum over slots of (prefill
        #: chunk grant | 1 decode token). Decode tokens always land; the
        #: budget throttles how much prefill may ride along per step.
        self.max_step_tokens = int(max_step_tokens) if max_step_tokens \
            else self.chunk + self.B - 1
        if self.max_step_tokens < 1:
            raise ValueError(f"max_step_tokens must be >= 1, got "
                             f"{self.max_step_tokens}")
        self._mesh = mesh
        #: tensor-parallel serving (the multichip subsystem, serving/
        #: cluster.py): a mesh with a "tp" axis turns the engine's KV
        #: buffers into REAL NamedShardings — kv-heads shard across the
        #: axis (the paged pool's head dim / the dense buffers' head
        #: dim), block tables and the allocator stay host-global, and
        #: logits/lens/tokens stay replicated (the step's in-graph
        #: sample consumes replicated logits, so the vocab-sharded lm
        #: head all-gathers exactly once per step). Any other mesh keeps
        #: the legacy multi-process behavior: replicated global buffers.
        self._tp_axis = None
        self._tp_size = 1
        if mesh is not None and "tp" in tuple(mesh.axis_names) \
                and int(mesh.shape["tp"]) > 1:
            self._tp_axis = "tp"
            self._tp_size = int(mesh.shape["tp"])
            if kvh % self._tp_size:
                raise ValueError(
                    f"num_key_value_heads {kvh} must divide by the tp "
                    f"mesh axis ({self._tp_size}) — kv-heads are the "
                    f"natural shard dim of the KV pools")
        if self.speculative_k > 1:
            # speculation is served by the fused scheduler's verify
            # grants (any cache backend) or the legacy dense scan; the
            # ONE remaining limitation is a tensor-parallel mesh
            if self._tp_axis is not None:
                raise ValueError(
                    "speculative_k > 1 under a tensor-parallel mesh is "
                    "the remaining speculation limitation: the verify "
                    "window's per-row lm-head gather has no TP wiring "
                    "yet — serve speculation single-chip, or drop to "
                    "speculative_k=1 on the TP replicas")
            if scheduler == "fused" and self.chunk < self.speculative_k:
                raise ValueError(
                    f"chunk_size {self.chunk} cannot carry a "
                    f"speculative_k={self.speculative_k} verify window "
                    f"(the fused mixed step's ids buffer is chunk "
                    f"tokens wide)")
        import ml_dtypes  # noqa: F401  (np.zeros understands bf16 via jnp)
        self._kvh = kvh
        self._head_dim = head_dim
        self._vocab = c.vocab_size
        self._np_dt = np.dtype(dt) if mesh is not None else dt
        self._n_layers = L
        if mesh is not None:
            from jax.sharding import PartitionSpec
            self._kv_spec = PartitionSpec(None, self._tp_axis) \
                if cache_impl == "paged" \
                else PartitionSpec(None, None, self._tp_axis)
        else:
            self._kv_spec = None
        self.cache_impl = cache_impl
        if enable_prefix_cache and cache_impl != "paged":
            raise ValueError("enable_prefix_cache needs cache_impl='paged' "
                             "(content-hashed block reuse lives in the "
                             "paged pool's table indirection; the dense "
                             "per-slot buffers have nothing to share)")
        self.prefix_cache = bool(enable_prefix_cache)
        if kv_cache_dtype is not None:
            if kv_cache_dtype not in ("int8", "int4"):
                raise ValueError(
                    f"unknown kv_cache_dtype {kv_cache_dtype!r} "
                    f"(supported: 'int8', 'int4', None)")
            if cache_impl != "paged":
                raise ValueError(
                    "kv_cache_dtype needs cache_impl='paged' — per-block "
                    "quantization scales live in the paged pool's block "
                    "granularity; the dense per-slot buffers have no "
                    "block to scale over")
        #: KV-pool quantization mode (None = bf16 pools, bit-identical
        #: to the pre-quantization engine)
        self.kv_quant = kv_cache_dtype
        # ---- host KV tier (DistServe/Splitwise-style memory tiering) --
        self.kv_host_swap = bool(kv_host_swap)
        self.kv_host_spill_bytes = int(kv_host_spill_bytes or 0)
        #: replica-independent sampling base key (None = pull one from
        #: the global generator at the first step) — SURVIVES reset()
        #: with the rest of the sampling-key contract
        self._sampling_seed = (int(sampling_seed)
                               if sampling_seed is not None else None)
        if self.kv_host_swap:
            if cache_impl != "paged":
                raise ValueError(
                    "kv_host_swap needs cache_impl='paged' — the host "
                    "tier swaps physical pool blocks; the dense per-slot "
                    "buffers have none")
            if scheduler != "fused":
                raise ValueError(
                    "kv_host_swap needs scheduler='fused' — re-admission "
                    "restores blocks and resumes the ramp at the stitch "
                    "position, which only the fused scheduler's "
                    "prefill_pos can express (legacy admission prefills "
                    "whole chunk trains)")
        if self.kv_host_spill_bytes:
            if cache_impl != "paged" or not enable_prefix_cache:
                raise ValueError(
                    "kv_host_spill_bytes needs cache_impl='paged' with "
                    "enable_prefix_cache=True — the spill store holds "
                    "LRU-EVICTED registered prefix blocks; without the "
                    "content store there is no eviction to spill")
        if cache_impl == "paged":
            if self.speculative_k > 1 and scheduler != "fused":
                raise ValueError(
                    "the legacy scheduler's speculative path is "
                    "dense-only — paged speculation rides the fused "
                    "scheduler's verify grants through the append-form "
                    "attention path (scheduler='fused')")
            self.block_size = int(block_size)
            if self.chunk % self.block_size:
                raise ValueError(f"chunk_size {self.chunk} must be a "
                                 f"multiple of block_size {self.block_size}")
            if self.capacity % self.chunk:
                raise ValueError(f"capacity {self.capacity} must be a "
                                 f"multiple of chunk_size {self.chunk} "
                                 f"under paged KV")
            self._max_blocks = self.capacity // self.block_size
            full = self.B * self._max_blocks
            self.n_blocks = int(kv_pool_blocks or full)
            #: pool-invariant debug audit (satellite): on under
            #: PADDLE_TPU_POOL_CHECKS=1 (the test suite sets it) —
            #: asserts free + cached + live-refcounted == n_blocks and
            #: table/refcount consistency after every alloc/free.
            self._debug_pool = os.environ.get(
                "PADDLE_TPU_POOL_CHECKS", "0") not in ("", "0")
        # ---- batched multi-LoRA (serving/adapters.py) ----------------
        #: host AdapterStore of registered low-rank deltas; None = the
        #: multi-tenant machinery is entirely absent (every program
        #: traces the pre-adapter body — bit-identical serving). With a
        #: store attached but EMPTY, dispatches still pass lora=None, so
        #: base output stays bit-identical until the first registration
        #: (which retraces the step programs exactly once).
        self.adapter_store = adapter_store
        self._adapter_slots = int(adapter_cache_slots)
        #: lazily-built AdapterDeviceCache (stacked device factors +
        #: LRU slot allocator); reset() drops it with the other device
        #: buffers and the next adapter dispatch rebuilds + re-swaps
        self.adapter_cache = None
        if adapter_store is not None:
            if self.speculative_k > 1 and scheduler != "fused":
                raise ValueError(
                    "the legacy speculative scan is not adapter-aware — "
                    "batched multi-LoRA speculation rides the fused "
                    "scheduler's verify grants (scheduler='fused')")
            if getattr(c, "fuse_attention_qkv", False) or \
                    getattr(c, "fuse_swiglu", False):
                raise ValueError(
                    "batched multi-LoRA targets the separate q/k/v and "
                    "gate/up projections — build the serving model "
                    "without fuse_attention_qkv/fuse_swiglu")
        self._hidden = c.hidden_size
        # admission-order stamps: the paged allocator's preempt-newest
        # invariant AND the fused scheduler's oldest-first budget walk
        self._admit_order = [0] * self.B
        self._admit_seq = 0
        self._init_device_state()

        # host-side slot table / queues
        self.slots: list[_Slot | None] = [None] * self.B
        self.waiting: collections.deque[GenerationRequest] = \
            collections.deque()
        self.finished_outputs: dict[int, RequestOutput] = {}
        self._next_id = 0
        #: tokens a preempted request committed before eviction, stitched
        #: back in front of its post-readmission stream at finish
        self._preempted_prefix = {}
        self._rng_key = None
        self._step_fn = None
        self._prefill_fn = None
        self._set_logits_fn = None
        self._set_pooled_fn = None
        #: outstanding step_begin() dispatches not yet step_finish()ed —
        #: the paged engine must stay at depth 1 (its host block allocator
        #: needs post-step lens before the next dispatch)
        self._inflight = 0
        #: optional FlightRecorder (profiler.flight_recorder): when
        #: attached and enabled, step_begin/step_finish emit one
        #: StepRecord per step and stamp every emitted token with its
        #: step id. None (the default) costs one attribute check per step.
        self.flight_recorder = None
        #: optional FaultInjector (serving.faults): scripted chaos
        #: schedules fire at the step_begin/step_finish hooks. None (the
        #: default) costs one attribute check per step.
        self.fault_injector = None
        self._rec_ctx = None       # per-step_begin wall-split anchors
        self._rec_preempted = []   # rids parked by _preempt_slot this step
        #: compiled multi-step decode programs, keyed by stride K (one
        #: program per distinct effective stride; survives reset())
        self._multi_fns = {}
        self._multi_step_factory = None
        #: compiled multi-window SPECULATIVE decode programs, keyed by
        #: stride (windows per dispatch); survives reset() like
        #: _multi_fns
        self._multi_spec_fns = {}
        self._multi_spec_factory = None
        #: rid -> draft-acceptance EWMA — the acceptance-adaptive
        #: verify-k state, SURVIVES reset() (like the rid counter and
        #: the sampling base key) so a supervised restart's re-admitted
        #: request resumes speculation at its learned window, not at the
        #: optimistic default. Entries drop at request finish.
        self._spec_ewma = {}
        #: seconds the CURRENT token's emit stamp should be backdated by
        #: (step_finish amortizes a k-row readout over the dispatch→sync
        #: window; 0.0 outside a readout walk and for 1-row steps) — the
        #: serving layer reads it inside its stream callback
        self.emit_backdate_s = 0.0
        self.stats = default_engine_stats()

    # ------------------------------------------------------------------
    # device state (built at __init__, REBUILT by reset())
    # ------------------------------------------------------------------
    def _make_zeros(self, shape, dtype, spec=None):
        if self._mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec
            sharding = NamedSharding(self._mesh, spec or PartitionSpec())
            shard = np.zeros(sharding.shard_shape(tuple(shape)), dtype)
            return jax.make_array_from_callback(
                shape, sharding, lambda idx: shard)
        return jnp.zeros(shape, dtype)

    def _init_device_state(self):
        """(Re)build every device-side buffer and the host allocator /
        content-store state from scratch. Called by ``__init__`` and by
        :meth:`reset` — after a crash the old buffers may be donated-away
        or mid-flight, so recovery rebuilds rather than trusts them. The
        compiled programs survive (same shapes, same shardings)."""
        L = self._n_layers
        if self.cache_impl == "paged":
            # +1 trailing SCRATCH block the allocator never hands out: the
            # Pallas paged-attention kernel's fused new-token write routes
            # invalid (-1) targets there — a freed slot keeps stale lens
            # with a wiped table row, and its garbage write must not land
            # on a real block (the XLA fallback drops such rows with an
            # out-of-range scatter; a kernel block write needs a real
            # destination)
            if self.kv_quant:
                # QUANTIZED pools: int8 payload (int4 nibble-packs two
                # head-dim elements per byte) + one fp32 scale per
                # (physical block, kv head), bundled as (pool, scale)
                # tuples so every step program, donation list and
                # sharding pin carries the pair as one pytree leaf-set.
                # Zero pools under zero scales dequantize to exact zeros
                # — the same cold state as the bf16 pools. The scale
                # array shares the paged _kv_spec (axis 1 = kv heads).
                from ..ops.kernels.paged_attention import kv_packed_dim
                dp = kv_packed_dim(self._head_dim, self.kv_quant)
                pool_shape = (self.n_blocks + 1, self._kvh,
                              self.block_size, dp)
                scale_shape = (self.n_blocks + 1, self._kvh)

                def quant_pool():
                    return (self._make_zeros(pool_shape, np.int8,
                                             self._kv_spec),
                            self._make_zeros(scale_shape, np.float32,
                                             self._kv_spec))

                self._k = [quant_pool() for _ in range(L)]
                self._v = [quant_pool() for _ in range(L)]
            else:
                pool_shape = (self.n_blocks + 1, self._kvh,
                              self.block_size, self._head_dim)
                self._k = [self._make_zeros(pool_shape, self._np_dt,
                                            self._kv_spec)
                           for _ in range(L)]
                self._v = [self._make_zeros(pool_shape, self._np_dt,
                                            self._kv_spec)
                           for _ in range(L)]
            self._tables = np.full((self.B, self._max_blocks), -1, np.int32)
            #: min-heap of free physical blocks: allocation always pops
            #: the SMALLEST free index, so physical layout is a pure
            #: function of the request/retirement sequence — repeated
            #: runs produce identical tables (the old list popped LIFO
            #: from the tail, making layout depend on retirement history
            #: and trace diffs noisy). list(range(n)) is already a heap.
            self._free_blocks = list(range(self.n_blocks))
            self._slot_blocks = [[] for _ in range(self.B)]
            #: per-block live reference count (prefix-cache sharing makes
            #: >1 possible; without it the count is only ever 0/1)
            self._block_ref = [0] * self.n_blocks
            # ---- content-addressed store (enable_prefix_cache) -------
            #: chain_hash -> phys for every REGISTERED full block; the
            #: hash chains over the whole prefix, so equal prefixes
            #: collide on purpose and the probe walk is one dict get per
            #: block
            self._store = {}
            self._block_hash = {}    # phys -> chain hash (registered)
            self._block_parent = {}  # phys -> parent chain hash
            self._block_tokens = {}  # phys -> block token ids (bytes)
            self._children = {}      # parent hash -> [phys, ...]
            #: refcount-0 registered blocks, oldest-freed first — the
            #: "cached" pool between live and free. Allocation evicts
            #: from HERE (oldest first) before any live slot is
            #: preempted.
            self._lru = collections.OrderedDict()
            # ---- stride-aware in-flight write fence ------------------
            #: phys -> number of IN-FLIGHT dispatches that may still
            #: write the block (stamped at step_begin over each active
            #: slot's committed-len..scheduled-stride span, dropped at
            #: that step's step_finish). The allocation ladder must
            #: never hand a fenced block to a new owner: a freed block
            #: still under fence parks in ``_quarantine`` instead of
            #: the free heap — this is what makes eviction/preemption
            #: safe while dispatches pipeline at depth > 1.
            self._write_fence = {}
            #: refcount-0 UNREGISTERED blocks whose fence has not
            #: cleared yet — released to the free heap by the
            #: step_finish that drops their last fence
            self._quarantine = set()
            # ---- host KV tier (kv_host_swap / kv_host_spill_bytes) ---
            #: rid -> swap entry (tokens covered, host block copies,
            #: tenant) for requests whose committed KV was demoted to
            #: host RAM at preemption. Entries drop at re-admission
            #: (consumed), at any terminal finish (_finish_tokens), and
            #: at reset() — a supervised restart re-prefills instead.
            self._swap_store = {}
            #: rid -> cumulative STITCH wall (s) of shipped-entry
            #: restores (the migration's last phase, timed where it
            #: actually runs — the decode replica's mixed step). The
            #: router folds it into its per-migration phase breakdown;
            #: entries drop with the rid's swap entry lifecycle.
            self._stitch_s = {}
            #: swap/spill entries whose device→host copies were issued
            #: but not yet materialized to numpy — drained in the
            #: step_begin/step_finish gap (the copy overlaps the step's
            #: device work) or on first use, whichever comes first
            self._swap_pending = []
            #: chain_hash -> spilled-block entry: the bounded host store
            #: LRU-evicted REGISTERED prefix blocks demote into (oldest
            #: spilled first out when the byte budget fills)
            self._spill = collections.OrderedDict()
            self._spill_bytes = 0
            # ---- cross-replica KV shipping (serving/kv_transport) ----
            #: rid -> staged EXPORT entry (tokens + tenant + per-layer
            #: block stacks + chain hashes), written by the engine
            #: thread at an export_kv-flagged request's finish site and
            #: popped by the router thread via export_kv(). Bounded:
            #: oldest entries drop when a router never collects.
            self._export_store = collections.OrderedDict()
            self._export_cap = 2 * self.B
            #: shipped PREFIX-block entries awaiting the engine thread
            #: (pull-on-miss imports land here from the router thread —
            #: a GIL-atomic list append — and drain into _spill at the
            #: top of the next step, before admission probes run)
            self._spill_inbox = []
        else:
            shape = (self.B, self.capacity, self._kvh, self._head_dim)
            self._k = [self._make_zeros(shape, self._np_dt, self._kv_spec)
                       for _ in range(L)]
            self._v = [self._make_zeros(shape, self._np_dt, self._kv_spec)
                       for _ in range(L)]
        self._logits = self._make_zeros((self.B, self._vocab), np.float32)
        self._lens = self._make_zeros((self.B,), np.int32)
        # device-side committed-token history (speculative mode): the
        # in-graph prompt-lookup draft reads it, decode windows append
        self._tokens = self._make_zeros((self.B, self.capacity), np.int32) \
            if self.speculative_k > 1 else None
        #: per-slot mean-pool accumulator for PREFILL-ONLY (embed)
        #: requests: each fused mixed step adds the sum of its granted
        #: prefill rows' final hidden states; the finishing readout
        #: divides by the prompt length. Zeroed per slot at admission.
        self._pooled = self._make_zeros((self.B, self._hidden), np.float32)
        #: the adapter device cache dies with the other device buffers
        #: (a crashed dispatch may have consumed its stacks through
        #: donation) — the next adapter dispatch rebuilds and re-swaps
        self.adapter_cache = None
        #: pool bytes incl. scale arrays, cached once per (re)build — the
        #: flight recorder stamps it on every StepRecord
        self._kv_nbytes = self.kv_pool_nbytes()

    def reset(self):
        """Tear the engine down to EMPTY and re-arm it — the supervised
        server's crash-recovery hook (``AsyncLLMServer(supervise=...)``).

        Every slot, waiting request, finished output, preemption stitch
        and (paged) pool/table/content-store binding drops; the device
        buffers are rebuilt from zeros (a crashed dispatch may have
        consumed the old ones through buffer donation, so they cannot be
        trusted or even touched) — on a quantized engine that includes
        the per-block scale arrays, rebuilt alongside their pools (zero
        scales over zero payloads dequantize to the same cold state). What SURVIVES: the compiled programs
        (identical shapes/shardings — a restart costs no recompile), the
        request-id counter (rids stay unique across restarts), the
        engine's cumulative ``stats``, the rid-keyed draft-acceptance
        EWMA mirror (a re-admitted speculative request resumes at its
        learned verify window), and the sampling base key — token ``p``
        of request ``r`` samples from ``fold_in(fold_in(key, r), p)``,
        so a re-admitted request's sampled stream continues exactly
        where the crash cut it. ``_check_pool_invariants`` holds
        trivially after a reset."""
        self._close_stride_guard()
        self._pool_owner = None
        self.slots = [None] * self.B
        self.waiting.clear()
        self.finished_outputs.clear()
        self._preempted_prefix.clear()
        self._inflight = 0
        self._admit_order = [0] * self.B
        self._rec_ctx = None
        self._rec_preempted = []
        self._init_device_state()
        if self.cache_impl == "paged":
            self._check_pool_invariants()
        return self

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _programs(self):
        if self._step_fn is not None:
            return
        model = self.model
        state = self._state
        B, cap, chunk = self.B, self.capacity, self.chunk
        top_k = self.top_k

        if self._tp_axis is not None:
            # TP sharding pins: KV buffer outputs keep the kv-head shard
            # (so donation round-trips in place and GSPMD never resolves
            # a step to a resharded layout), everything the HOST reads
            # (tokens, carried logits, lens) pins replicated — the
            # vocab-sharded lm head all-gathers into the logits exactly
            # once per step, and np.asarray readouts see full replicas.
            from jax.sharding import NamedSharding, PartitionSpec as _P
            _kv_sh = NamedSharding(
                self._mesh,
                _P(None, self._tp_axis) if self.cache_impl == "paged"
                else _P(None, None, self._tp_axis))
            _rep_sh = NamedSharding(self._mesh, _P())

            def _pin_kv(bufs):
                # tree_map: a quantized pool entry is a (payload, scale)
                # TUPLE — the paged P(None, tp) spec pins both (axis 1 is
                # kv heads on the 4-D payload and the 2-D scale alike)
                return jax.tree_util.tree_map(
                    lambda b: jax.lax.with_sharding_constraint(b, _kv_sh),
                    list(bufs))

            def _pin_rep(x):
                return jax.lax.with_sharding_constraint(x, _rep_sh)
        else:
            def _pin_kv(bufs):
                return bufs

            def _pin_rep(x):
                return x

        kvq = self.kv_quant

        def paged_caches(kb, vb, tables, lens, q_lens=None):
            """Per-layer PagedKVCache list of one traced dispatch — THE
            one place that unpacks the quantized (payload, scale) pool
            bundles, so no step body can forget the scales."""
            from ..models.llama import PagedKVCache
            if kvq:
                return [PagedKVCache(k[0], v[0], tables, lens, q_lens,
                                     k_scale=k[1], v_scale=v[1],
                                     quant=kvq)
                        for k, v in zip(kb, vb)]
            return [PagedKVCache(k, v, tables, lens, q_lens)
                    for k, v in zip(kb, vb)]

        def unpack_kv(new_caches):
            """Updated (k_bufs, v_bufs) lists off a model call's returned
            caches — re-bundling (payload, scale) tuples on quantized
            engines. Works for every cache class (dense slot buffers
            have no scales and kvq is then always None)."""
            def val(x):
                return x._value if isinstance(x, Tensor) else x
            if kvq:
                return ([(val(cc.k), val(cc.k_scale)) for cc in new_caches],
                        [(val(cc.v), val(cc.v_scale)) for cc in new_caches])
            return ([val(cc.k) for cc in new_caches],
                    [val(cc.v) for cc in new_caches])

        K = self.horizon

        def sample_next(logits, key, temps, top_ps, rids, lens):
            """THE sample-from-carried-logits prologue: greedy rows argmax,
            sampling rows the filtered categorical, per-slot select. One
            copy consumed by one_step, the spec verify windows, AND the
            fused mixed step (the carried-logits fix once had to be
            applied in several copies of this code).

            Sampling keys derive as ``fold_in(fold_in(key, rid), pos)``
            instead of advancing one global split stream: the token
            sampled at position ``pos`` of request ``rid`` is a pure
            function of (engine base key, rid, position), so batch
            composition, pool-pressure preemption replay, and supervised
            engine RESTART (the fault-tolerance layer's token-exact
            resumption) cannot change a sampled stream. Greedy rows never
            consult the key, and EVERY path — including the speculative
            verify windows, whose coupled acceptance rule re-derives the
            same per-position keys instead of advancing a shared stream —
            leaves ``key`` untouched across steps, so resumption is
            token-exact in sampled mode too (docs/architecture.md)."""
            greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            keys = jax.vmap(lambda r, p: jax.random.fold_in(
                jax.random.fold_in(key, r), p))(rids, lens)
            sampled = jax.vmap(
                lambda k, row, t, tp: _sample_logits_device(
                    row, k, jnp.maximum(t, 1e-6), top_k, tp, False, True)
            )(keys, logits, temps, top_ps)
            return jnp.where(temps <= 0.0, greedy_tok, sampled)

        def one_step(k_bufs, v_bufs, logits, lens, active, rng, state_vals,
                     temps, top_ps, eos_ids, rids, tables, lora=None):
            """sample from current logits -> one-token model step.
            ``tables`` selects the cache backend at TRACE time: None ->
            dense SlotKVCache slot buffers; a [B, MB] array -> PagedKVCache
            block pool (ONE body serves both engines). ``lora`` (batched
            multi-LoRA): the traced adapter stacks + per-slot device
            rows — the scope adds the gathered delta to every llama
            projection; None traces the exact pre-adapter body."""
            nxt = sample_next(logits, rng, temps, top_ps, rids, lens)
            # inactive slots decode garbage; pin them to token 0
            nxt = jnp.where(active, nxt, 0)
            with functional_mode(), _bind(state, state_vals), \
                    lora_scope(lora):
                if tables is None:
                    caches = [SlotKVCache(k, v, lens)
                              for k, v in zip(k_bufs, v_bufs)]
                else:
                    caches = paged_caches(k_bufs, v_bufs, tables, lens)
                hidden, new_caches = model.llama(
                    Tensor(nxt[:, None]), kv_caches=caches,
                    position_offset=Tensor(lens))
                new_logits = model._logits(hidden)._value[:, 0] \
                    .astype(jnp.float32)
            # an INACTIVE row's carried logits must survive the remaining
            # scan iterations — a slot deactivated non-terminally (pool
            # budget clamp) samples from them next step
            new_logits = jnp.where(active[:, None], new_logits, logits)
            kb, vb = unpack_kv(new_caches)
            new_lens = jnp.where(active, lens + 1, lens)
            finished = active & (nxt == eos_ids)
            return nxt, new_logits, kb, vb, new_lens, finished, rng

        def step(state_vals, k_bufs, v_bufs, logits, lens, active, rng,
                 temps, top_ps, eos_ids, budgets, rids, tables=None,
                 lora=None):
            """`horizon` decode iterations as ONE compiled lax.scan — the
            host sync (and through a tunnel, the RTT) amortizes over K
            tokens per slot. A slot that hits eos, capacity, or its
            remaining budget mid-horizon deactivates in-graph; the host
            reads the per-iteration (tokens, active) history to attribute
            outputs. ``tables`` (paged mode) is a traced input — the host
            allocator mutates it between steps without recompiling."""
            def body(carry, _):
                kb, vb, logits, lens, act, emitted, rng = carry
                nxt, logits, kb, vb, lens, finished, rng = one_step(
                    kb, vb, logits, lens, act, rng, state_vals, temps,
                    top_ps, eos_ids, rids, tables, lora)
                emitted = emitted + act.astype(jnp.int32)
                act_next = act & ~finished & (lens < cap - 1) & \
                    (emitted < budgets)
                return (kb, vb, logits, lens, act_next, emitted, rng), \
                    (nxt, act)

            emitted0 = jnp.zeros_like(lens)
            (k_bufs, v_bufs, logits, lens, active, _, rng), \
                (toks, was_active) = jax.lax.scan(
                    body,
                    (k_bufs, v_bufs, logits, lens, active, emitted0, rng),
                    None, length=K)
            return (_pin_rep(toks), _pin_rep(was_active), _pin_rep(logits),
                    _pin_kv(k_bufs), _pin_kv(v_bufs), _pin_rep(lens), rng)

        def make_multi_step(Kms):
            """Build the ``readout_stride=Kms`` MULTI-STEP decode
            program: up to Kms one_step iterations as ONE dispatch, as a
            ``lax.while_loop`` that EARLY-EXITS IN-GRAPH the moment no
            slot is active (every slot hit eos / its budget / capacity)
            — unlike the horizon scan, a batch that finishes 1 step into
            a 4-step stride pays 1 step of device compute, not 4. Token
            and activity rows land in [Kms, B] buffers (rows past the
            exit stay zero/inactive, which the shared readout walk
            already skips), so step_finish drains the whole stride in
            the same single [rows, B] device→host sync."""
            def multi_step(state_vals, k_bufs, v_bufs, logits, lens,
                           active, rng, temps, top_ps, eos_ids, budgets,
                           rids, tables=None, lora=None):
                nL = len(k_bufs)

                def cond(carry):
                    i = carry[0]
                    act = carry[5]
                    return (i < Kms) & jnp.any(act)

                def body(carry):
                    i, kb, vb, lg, ln, act, emitted, toks, wa = carry
                    nxt, lg, kb, vb, ln, finished, _ = one_step(
                        kb, vb, lg, ln, act, rng, state_vals, temps,
                        top_ps, eos_ids, rids, tables, lora)
                    toks = jax.lax.dynamic_update_slice(
                        toks, nxt[None], (i, jnp.int32(0)))
                    wa = jax.lax.dynamic_update_slice(
                        wa, act[None], (i, jnp.int32(0)))
                    emitted = emitted + act.astype(jnp.int32)
                    act = act & ~finished & (ln < cap - 1) & \
                        (emitted < budgets)
                    return (i + 1, kb, vb, lg, ln, act, emitted, toks, wa)

                carry = (jnp.int32(0), list(k_bufs), list(v_bufs), logits,
                         lens, jnp.asarray(active),
                         jnp.zeros_like(lens),
                         jnp.zeros((Kms, B), jnp.int32),
                         jnp.zeros((Kms, B), bool))
                (_, k_out, v_out, logits, lens, _, _, toks, wa) = \
                    jax.lax.while_loop(cond, body, carry)
                assert len(k_out) == nL
                return (_pin_rep(toks), _pin_rep(wa), _pin_rep(logits),
                        _pin_kv(k_out), _pin_kv(v_out), _pin_rep(lens),
                        rng)
            return multi_step

        self._multi_step_factory = make_multi_step

        Kspec = self.speculative_k
        ngram = self.lookup_ngram

        def row_sample(logits_rows, key, temps, top_ps, rids, poss):
            """Per-(row, position) COUPLED sampler: the token the engine
            would commit at each position — greedy rows argmax, sampled
            rows the filtered categorical under the per-(rid, position)
            fold_in key, i.e. EXACTLY the key ``sample_next`` would use
            when the stream reaches that position one token at a time.
            ``logits_rows`` [B, R, V], ``poss`` [B, R] -> [B, R] int32.
            The verify rule is built on this coupling: a draft is
            accepted iff it EQUALS this token, so a speculative stream
            is token-identical to the non-spec engine's — greedy AND
            sampled — and restart/failover resumption needs no
            acceptance-randomness replay (there is none)."""
            greedy_tok = jnp.argmax(logits_rows, axis=-1).astype(jnp.int32)

            def per_slot(k_rid, rows, t, tp, ps):
                return jax.vmap(lambda p, row: _sample_logits_device(
                    row, jax.random.fold_in(k_rid, p),
                    jnp.maximum(t, 1e-6), top_k, tp, False, True))(ps, rows)

            k_rids = jax.vmap(lambda r: jax.random.fold_in(key, r))(rids)
            sampled = jax.vmap(per_slot)(k_rids, logits_rows, temps,
                                         top_ps, poss)
            return jnp.where((temps <= 0.0)[:, None], greedy_tok, sampled)

        def verify_window(logits_win, draft, lens, q_eff, key, temps,
                          top_ps, rids, active):
            """Coupled acceptance over ONE verify window. ``logits_win``
            [B, Kw, V] are the model's logits over the window rows
            (row j = the distribution for position lens+1+j given the
            window prefix), ``draft`` [B, Kw-1] the prompt-lookup
            proposals, ``q_eff`` the per-slot granted window width (1 +
            drafts; rows past it are padding and never accept). Draft j
            survives iff it equals the COUPLED sample at its position
            and every earlier draft did. Returns ``(counts, n_acc,
            next_logits)``: committed tokens per slot (1 + accepted
            drafts), accepted-draft counts, and the carried logits at
            the last accepted row — the distribution the NEXT committed
            token samples from, which by the coupling is exactly the
            non-spec engine's carried-logits state."""
            Kd = draft.shape[1]
            poss = lens[:, None] + 1 + \
                jnp.arange(Kd, dtype=jnp.int32)[None, :]
            targets = row_sample(logits_win[:, :Kd], key, temps, top_ps,
                                 rids, poss)
            acc = (targets == draft) & \
                (jnp.arange(Kd)[None, :] < (q_eff - 1)[:, None])
            n_acc = jnp.cumprod(acc.astype(jnp.int32), axis=1) \
                .sum(axis=1).astype(jnp.int32)
            counts = jnp.where(active, 1 + n_acc, 0)
            next_logits = jnp.take_along_axis(
                logits_win, n_acc[:, None, None], axis=1)[:, 0]
            return counts, n_acc, next_logits

        def spec_step(state_vals, k_bufs, v_bufs, logits, lens, active, rng,
                      temps, top_ps, eos_ids, budgets, rids, tokens_buf):
            """`horizon` speculative verify windows as ONE compiled scan.
            Each window: in-graph prompt-lookup draft from the device token
            history -> commit one sampled token + verify the Kspec-1 drafts
            with ONE Kspec-token model call (verify_window: COUPLED
            acceptance, so greedy and sampled streams are both token-exact
            vs plain decode and the key never advances). KV written past
            the accepted prefix is stale but unreferenced (lens-based
            masks) and is overwritten by the next window."""
            def body(carry, _):
                kb, vb, logits, lens, act, emitted, rng, tbuf = carry
                draft = _lookup_draft(tbuf, lens, Kspec - 1, ngram)
                committed = sample_next(logits, rng, temps, top_ps, rids,
                                        lens)
                committed = jnp.where(act, committed, 0)
                window = jnp.concatenate([committed[:, None], draft],
                                         axis=1)
                with functional_mode(), _bind(state, state_vals):
                    caches = [SlotKVCache(k, v, lens)
                              for k, v in zip(kb, vb)]
                    hidden, new_caches = model.llama(
                        Tensor(window), kv_caches=caches,
                        position_offset=Tensor(lens))
                    logits_all = model._logits(hidden)._value \
                        .astype(jnp.float32)                # [B, K, V]
                kb = [cc.k._value if isinstance(cc.k, Tensor) else cc.k
                      for cc in new_caches]
                vb = [cc.v._value if isinstance(cc.v, Tensor) else cc.v
                      for cc in new_caches]
                counts, _, new_logits = verify_window(
                    logits_all, draft, lens,
                    jnp.where(act, Kspec, 0), rng, temps, top_ps, rids,
                    act)
                new_logits = jnp.where(act[:, None], new_logits, logits)
                new_lens = lens + counts
                tbuf = _write_window(tbuf, window, lens)
                emitted = emitted + counts
                kidx = jnp.arange(Kspec)[None, :]
                in_window = kidx < counts[:, None]
                eos_hit = jnp.any(
                    in_window & (window == eos_ids[:, None]), axis=1)
                act_next = act & ~eos_hit & \
                    (new_lens < cap - Kspec) & (emitted < budgets)
                return (kb, vb, new_logits, new_lens, act_next, emitted,
                        rng, tbuf), (window, counts, act)

            emitted0 = jnp.zeros_like(lens)
            (k_bufs, v_bufs, logits, lens, active, _, rng, tokens_buf), \
                (toks, counts, was_active) = jax.lax.scan(
                    body,
                    (k_bufs, v_bufs, logits, lens, active, emitted0, rng,
                     tokens_buf),
                    None, length=K)
            return (_pin_rep(toks), _pin_rep(counts), _pin_rep(was_active),
                    _pin_rep(logits), _pin_kv(k_bufs), _pin_kv(v_bufs),
                    _pin_rep(lens), rng, tokens_buf)

        def make_multi_spec(Kms):
            """Build the fused ALL-DECODE speculative program for stride
            ``Kms``: up to Kms verify windows per slot as ONE dispatch,
            as a ``lax.while_loop`` with the multi-step path's IN-GRAPH
            EARLY EXIT (every slot hit eos / budget / capacity / its
            covered blocks -> the loop stops on device). Each window
            runs through the APPEND-form attention path (q_lens = the
            granted 1 + k drafts per slot, shrunk in-graph to the
            per-slot ``row_caps`` coverage budget), verifies with the
            coupled rule, and rolls rejected tokens back via lens.
            Token/count/activity rows land in [Kms, B, Kspec] /
            [Kms, B] buffers — the same layout the legacy verify scan
            hands step_finish, so ONE spec readout serves both."""
            Kd = Kspec - 1

            def multi_spec(state_vals, k_bufs, v_bufs, logits, lens,
                           active, rng, temps, top_ps, eos_ids, budgets,
                           rids, spec_qs, row_caps, tokens_buf,
                           tables=None, lora=None):
                nL = len(k_bufs)

                def cond(carry):
                    return (carry[0] < Kms) & jnp.any(carry[5])

                def body(carry):
                    (i, kb, vb, lg, ln, act, emitted, tbuf, toks, cnts,
                     wa, qs) = carry
                    # pipelined over-dispatch guard: a slot whose
                    # PREVIOUS (still in-flight) dispatch grew it to the
                    # capacity margin deactivates before its window (or
                    # its token-history write) could cross the buffer
                    act = act & (ln + Kspec <= cap)
                    draft = _lookup_draft(tbuf, ln, Kd, ngram)
                    committed = sample_next(lg, rng, temps, top_ps, rids,
                                            ln)
                    committed = jnp.where(act, committed, 0)
                    window = jnp.concatenate([committed[:, None], draft],
                                             axis=1)
                    # per-slot window width: the granted 1 + k drafts,
                    # shrunk in-graph to the covered-block / capacity
                    # row budget (pool pressure narrows windows before
                    # anyone is preempted)
                    q_eff = jnp.clip(jnp.minimum(row_caps, cap) - ln, 0,
                                     spec_qs)
                    q_eff = jnp.where(act, q_eff, 0)
                    act = act & (q_eff >= 1)
                    q_eff = jnp.where(act, q_eff, 0)
                    with functional_mode(), _bind(state, state_vals), \
                            lora_scope(lora):
                        if tables is None:
                            from ..models.llama import ChunkKVCache
                            caches = [ChunkKVCache(k, v, ln, q_eff)
                                      for k, v in zip(kb, vb)]
                        else:
                            caches = paged_caches(kb, vb, tables, ln,
                                                  q_eff)
                        hidden, new_caches = model.llama(
                            Tensor(window), kv_caches=caches,
                            position_offset=Tensor(ln))
                        logits_win = model._logits(hidden)._value \
                            .astype(jnp.float32)          # [B, Kspec, V]
                    kb, vb = unpack_kv(new_caches)
                    counts, _, new_lg = verify_window(
                        logits_win, draft, ln, q_eff, rng, temps, top_ps,
                        rids, act)
                    new_lg = jnp.where(act[:, None], new_lg, lg)
                    new_ln = ln + counts
                    tb_new = _write_window(tbuf, window, ln)
                    tbuf = jnp.where(act[:, None], tb_new, tbuf)
                    toks = jax.lax.dynamic_update_slice(
                        toks, window[None], (i, jnp.int32(0),
                                             jnp.int32(0)))
                    cnts = jax.lax.dynamic_update_slice(
                        cnts, counts[None], (i, jnp.int32(0)))
                    wa = jax.lax.dynamic_update_slice(
                        wa, act[None], (i, jnp.int32(0)))
                    qs = jax.lax.dynamic_update_slice(
                        qs, q_eff[None], (i, jnp.int32(0)))
                    emitted = emitted + counts
                    kidx = jnp.arange(Kspec)[None, :]
                    in_win = kidx < counts[:, None]
                    eos_hit = jnp.any(
                        in_win & (window == eos_ids[:, None]), axis=1)
                    act = act & ~eos_hit & (new_ln < cap - Kspec) & \
                        (emitted < budgets)
                    return (i + 1, kb, vb, new_lg, new_ln, act, emitted,
                            tbuf, toks, cnts, wa, qs)

                carry = (jnp.int32(0), list(k_bufs), list(v_bufs), logits,
                         lens, jnp.asarray(active), jnp.zeros_like(lens),
                         tokens_buf,
                         jnp.zeros((Kms, B, Kspec), jnp.int32),
                         jnp.zeros((Kms, B), jnp.int32),
                         jnp.zeros((Kms, B), bool),
                         jnp.zeros((Kms, B), jnp.int32))
                (_, k_out, v_out, logits, lens, _, _, tokens_buf, toks,
                 cnts, wa, qs) = jax.lax.while_loop(cond, body, carry)
                assert len(k_out) == nL
                return (_pin_rep(toks), _pin_rep(cnts), _pin_rep(wa),
                        _pin_rep(logits), _pin_kv(k_out), _pin_kv(v_out),
                        _pin_rep(lens), rng, tokens_buf, _pin_rep(qs))
            return multi_spec

        self._multi_spec_factory = make_multi_spec

        def fused_step(state_vals, k_bufs, v_bufs, logits, lens, rng, ids,
                       q_lens, is_decode, active, temps, top_ps, rids,
                       tables=None, lora=None, is_embed=None, pooled=None,
                       tokens_buf=None, spec_ks=None):
            """ONE mixed prefill+decode dispatch (the fused scheduler's
            step): slot b processes rows [0, q_lens[b]) of ``ids`` —
            either a prefill chunk (host-provided prompt rows) or one
            decode token (row 0, sampled IN-GRAPH from the carried
            logits, so no extra host round-trip vs the plain step).
            Every slot's rows sit at its own absolute positions
            (``lens``); padding rows write nothing (drop-scatter) and
            their outputs are never read. ``tables`` selects the cache
            backend at trace time exactly like ``step``; ``lora`` arms
            the per-slot adapter delta exactly like ``one_step``.

            ``pooled``/``is_embed`` (prefill-only grant kind): when an
            EMBED slot is resident, its granted prefill rows' final
            hidden states accumulate into its ``pooled`` row — the
            mean-pool numerator the finishing readout divides by the
            prompt length. Passed as None on generate-only dispatches,
            so the no-embed program is untouched.

            ``tokens_buf``/``spec_ks`` (VERIFY grant kind — the fused
            speculative engine): a decode slot with ``spec_ks[b] = k >
            0`` was granted a k-draft verify window (``q_lens[b] = k+1``)
            — row 0 is its committed sample, rows 1..k the in-graph
            prompt-lookup drafts read from the device token history, the
            whole window runs through the SAME append-form attention as
            a prefill chunk, and the coupled ``verify_window`` rule
            commits the matching prefix (rejected tokens roll back via
            lens; their KV rows are stale-but-unreferenced). Passed as
            None on non-speculative engines, so the spec-free program —
            and ``speculative_k=1`` serving — is bit-identical."""
            nxt = sample_next(logits, rng, temps, top_ps, rids, lens)
            # capacity guard for pipelined over-dispatch: a window that
            # would cross the buffer end deactivates in-graph
            active = active & (lens + q_lens <= cap)
            dec = active & is_decode
            if spec_ks is None:
                nxt = jnp.where(dec, nxt, 0)
                q_eff = jnp.where(active, q_lens, 0)
                row0 = jnp.arange(chunk, dtype=jnp.int32)[None, :] == 0
                ids = jnp.where(dec[:, None] & row0, nxt[:, None], ids)
            else:
                # verify windows must fit the token-history write below;
                # a clamped-out verify slot goes fully inactive (its
                # rows must not scatter) — in practice the readout's
                # capacity margin retires slots before this fires
                dec = dec & (lens + Kspec <= cap)
                active = active & (~is_decode | dec)
                nxt = jnp.where(dec, nxt, 0)
                q_eff = jnp.where(active, q_lens, 0)
                draft = _lookup_draft(tokens_buf, lens, Kspec - 1, ngram)
                window = jnp.concatenate([nxt[:, None], draft], axis=1)
                wcols = jnp.arange(chunk, dtype=jnp.int32)[None, :] < Kspec
                padded_win = jnp.zeros_like(ids) \
                    .at[:, :Kspec].set(window)
                ids = jnp.where(dec[:, None] & wcols, padded_win, ids)
                tb_new = _write_window(tokens_buf, window, lens)
                tokens_buf = jnp.where(dec[:, None], tb_new, tokens_buf)
            with functional_mode(), _bind(state, state_vals), \
                    lora_scope(lora):
                if tables is None:
                    from ..models.llama import ChunkKVCache
                    caches = [ChunkKVCache(k, v, lens, q_eff)
                              for k, v in zip(k_bufs, v_bufs)]
                else:
                    caches = paged_caches(k_bufs, v_bufs, tables, lens,
                                          q_eff)
                hidden, new_caches = model.llama(
                    Tensor(ids), kv_caches=caches,
                    position_offset=Tensor(lens))
                # per-slot LAST VALID row: a prefill chunk's next-token
                # logits / the decode token's next logits — one gather,
                # then the lm head over [B, 1, H] only (never the full
                # chunk: the head over B*chunk rows would dominate)
                rows = jnp.take_along_axis(
                    hidden._value,
                    jnp.maximum(q_eff - 1, 0)[:, None, None], axis=1)
                new_logits = model._logits(Tensor(rows))._value[:, 0] \
                    .astype(jnp.float32)
                if spec_ks is not None:
                    # verify slots need PER-ROW logits over the window
                    # (not just the last valid row): the head runs over
                    # [B, Kspec, H] — bounded by the window width, never
                    # the full chunk
                    logits_win = model._logits(
                        Tensor(hidden._value[:, :Kspec]))._value \
                        .astype(jnp.float32)
            if pooled is not None:
                # masked sum of this dispatch's real prefill rows for
                # embed slots only, fp32 — one tiny [B,S,H]x[B,S]
                # contraction riding the mixed step
                rows_real = jnp.arange(chunk, dtype=jnp.int32)[None, :] \
                    < q_eff[:, None]
                emb_mask = (rows_real & is_embed[:, None]
                            & ~is_decode[:, None]).astype(jnp.float32)
                pooled = pooled + jnp.einsum(
                    "bsh,bs->bh", hidden._value.astype(jnp.float32),
                    emb_mask)
            kb, vb = unpack_kv(new_caches)
            if spec_ks is None:
                new_logits = jnp.where(active[:, None], new_logits, logits)
                new_lens = lens + q_eff
                # [1, B] token/activity rows: the readout walk in
                # step_finish is shared with the scan-based steps (K==1)
                return (_pin_rep(nxt[None]), _pin_rep(dec[None]),
                        _pin_rep(new_logits), _pin_kv(kb), _pin_kv(vb),
                        _pin_rep(new_lens), rng, pooled)
            counts, _, spec_logits = verify_window(
                logits_win, draft, lens, q_eff, rng, temps, top_ps,
                rids, dec)
            new_logits = jnp.where(dec[:, None], spec_logits, new_logits)
            new_logits = jnp.where(active[:, None], new_logits, logits)
            # rejected drafts ROLL BACK here: a verify slot's lens grow
            # by its committed count, not its granted window — the
            # written-past-committed KV rows are stale but unreferenced
            # (lens-based masks) and the next window overwrites them
            new_lens = lens + jnp.where(dec, counts, q_eff)
            # [1, B, Kspec] window layout + [1, B] counts: the spec
            # readout flatten in step_finish is shared with the legacy
            # verify scan (one window here). The offered widths ride
            # along so the acceptance accounting books exact proposals.
            return (_pin_rep(window[None]), _pin_rep(counts[None]),
                    _pin_rep(dec[None]), _pin_rep(new_logits),
                    _pin_kv(kb), _pin_kv(vb), _pin_rep(new_lens), rng,
                    pooled, tokens_buf, _pin_rep(q_eff[None]))

        def prefill_chunk(state_vals, k_bufs, v_bufs, ids, slot, off, last,
                          lora=None):
            """Run chunk `ids` [1, chunk] of one prompt through the model
            against slot `slot`'s KV region starting at position `off`;
            returns updated buffers + the logits at in-chunk row `last`.
            ``lora``: the single-sequence adapter pack (slots vector of
            length 1) — prefill KV must carry the tenant's deltas."""
            from ..models.llama import StaticKVCache

            z = jnp.int32(0)
            k_slot = [jax.lax.dynamic_slice(
                k, (slot, z, z, z), (1,) + k.shape[1:]) for k in k_bufs]
            v_slot = [jax.lax.dynamic_slice(
                v, (slot, z, z, z), (1,) + v.shape[1:]) for v in v_bufs]
            with functional_mode(), _bind(state, state_vals), \
                    lora_scope(lora):
                caches = [StaticKVCache(k, v)
                          for k, v in zip(k_slot, v_slot)]
                hidden, new_caches = model.llama(
                    Tensor(ids), kv_caches=caches,
                    position_offset=Tensor(off))
                row = jax.lax.dynamic_slice(
                    hidden._value, (z, last, z), (1, 1, hidden.shape[-1]))
                logits_row = model._logits(Tensor(row))._value[0, 0] \
                    .astype(jnp.float32)
            k_out = [jax.lax.dynamic_update_slice(
                kb, (cc.k._value if isinstance(cc.k, Tensor) else cc.k
                     ).astype(kb.dtype), (slot, z, z, z))
                for kb, cc in zip(k_bufs, new_caches)]
            v_out = [jax.lax.dynamic_update_slice(
                vb, (cc.v._value if isinstance(cc.v, Tensor) else cc.v
                     ).astype(vb.dtype), (slot, z, z, z))
                for vb, cc in zip(v_bufs, new_caches)]
            return _pin_kv(k_out), _pin_kv(v_out), _pin_rep(logits_row)

        def set_logits(logits, row, slot):
            return jax.lax.dynamic_update_slice(
                logits, row[None].astype(logits.dtype), (slot, jnp.int32(0)))

        if self.cache_impl == "paged":
            from ..models.llama import PagedKVCache, StaticKVCache
            bs_blk = self.block_size
            MB = self._max_blocks

            head_d = self._head_dim

            def prefill_chunk_paged(state_vals, k_pools, v_pools, ids,
                                    table_row, off, last, lora=None):
                """Paged chunked prefill: gather the slot's logical KV from
                its blocks, run the chunk like the dense path, scatter the
                chunk's new KV back into the (block-aligned) blocks.
                Quantized pools gather DEQUANTIZED (f32) and scatter back
                re-quantized: each written block is whole-chunk content,
                so its fresh per-head absmax scale needs no merge with
                old rows."""
                from ..ops.kernels.paged_attention import (
                    kv_block_scale, kv_quantize, kv_unpack)
                z = jnp.int32(0)
                safe = jnp.maximum(table_row, 0)

                def gather(p):
                    if kvq:
                        blks = kv_unpack(p[0][safe], kvq, head_d) * \
                            p[1][safe][..., None, None]
                    else:
                        blks = p[safe]
                    return jnp.moveaxis(blks, 2, 1).reshape(
                        1, MB * bs_blk, blks.shape[1], blks.shape[3])

                k_slot = [gather(p) for p in k_pools]
                v_slot = [gather(p) for p in v_pools]
                with functional_mode(), _bind(state, state_vals), \
                        lora_scope(lora):
                    caches = [StaticKVCache(k, v)
                              for k, v in zip(k_slot, v_slot)]
                    hidden, new_caches = model.llama(
                        Tensor(ids), kv_caches=caches,
                        position_offset=Tensor(off))
                    row = jax.lax.dynamic_slice(
                        hidden._value, (z, last, z),
                        (1, 1, hidden.shape[-1]))
                    logits_row = model._logits(Tensor(row))._value[0, 0] \
                        .astype(jnp.float32)

                def scatter(pool, cc_val):
                    # chunk rows [off, off+chunk) -> chunk//bs_blk blocks,
                    # as ONE batched scatter (the old per-logical-block
                    # Python loop traced O(chunk/block_size) sequential
                    # dynamic_update_slice ops per prompt chunk)
                    new_rows = jax.lax.dynamic_slice(
                        cc_val, (z, off, z, z),
                        (1, chunk) + cc_val.shape[2:])[0]   # [chunk, H, D]
                    nblk = chunk // bs_blk
                    h, d = new_rows.shape[1], new_rows.shape[2]
                    blks = jnp.swapaxes(
                        new_rows.reshape(nblk, bs_blk, h, d), 1, 2)
                    phys = jax.lax.dynamic_slice(
                        table_row, (off // bs_blk,), (nblk,))
                    if kvq:
                        payload, scales = pool
                        blks = blks.astype(jnp.float32)
                        # zero the chunk's PADDING rows (chunk index >
                        # last): their token-id-0 KV must not ride the
                        # absmax scale — and the stored bytes then match
                        # what the fused append path writes for the same
                        # prefix (it never writes padding rows at all)
                        ridx = jnp.arange(nblk)[:, None] * bs_blk + \
                            jnp.arange(bs_blk)[None, :]    # [nblk, bs]
                        dead = (ridx > last)[:, None, :, None]
                        blks = jnp.where(dead, jnp.float32(0.0), blks)
                        s_new = kv_block_scale(blks, kvq,
                                               axes=(2, 3))  # [nblk, H]
                        return (payload.at[phys].set(
                                    kv_quantize(blks, s_new[..., None,
                                                            None], kvq)),
                                scales.at[phys].set(s_new))
                    return pool.at[phys].set(blks.astype(pool.dtype))

                k_out = [scatter(p, (cc.k._value if isinstance(cc.k, Tensor)
                                     else cc.k))
                         for p, cc in zip(k_pools, new_caches)]
                v_out = [scatter(p, (cc.v._value if isinstance(cc.v, Tensor)
                                     else cc.v))
                         for p, cc in zip(v_pools, new_caches)]
                return _pin_kv(k_out), _pin_kv(v_out), _pin_rep(logits_row)

            self._prefill_paged_fn = jax.jit(prefill_chunk_paged,
                                             donate_argnums=(1, 2))

            def cow_copy(k_pools, v_pools, src, dst):
                """Copy-on-write block duplication: clone physical block
                ``src`` into ``dst`` across every layer's K/V pool. One
                jitted program, src/dst traced — no recompile per copy.
                Block-index ops only, so under TP each shard clones its
                own kv-head slice — no cross-shard traffic. tree_map
                clones a quantized pool's payload AND its per-block
                scale row in one rule (scale[src] is block src's row —
                the clone is bit-exact, so COW never re-rounds)."""
                def cp(p):
                    return p.at[dst].set(p[src])
                return (_pin_kv(jax.tree_util.tree_map(cp, list(k_pools))),
                        _pin_kv(jax.tree_util.tree_map(cp, list(v_pools))))

            self._cow_fn = jax.jit(cow_copy, donate_argnums=(0, 1))

            def kv_gather_blocks(k_pools, v_pools, idx):
                """Host-tier STAGING gather: physical blocks ``idx`` out
                of every layer's K/V pool as fresh arrays the host can
                then copy down (swap-out / spill). tree_map's one rule
                carries a quantized pool's payload AND its per-block
                scale rows, so int8/int4 content round-trips bit-exact.
                Reads only — and its input is the engine's NEWEST pool
                futures, so it is sequenced after every already-
                dispatched write (the committed content has landed by
                construction) and before any later owner's writes
                (program order over the shared pool buffers — the same
                argument _cow_tail documents)."""
                def g(p):
                    return p[idx]
                return (jax.tree_util.tree_map(g, list(k_pools)),
                        jax.tree_util.tree_map(g, list(v_pools)))

            self._kv_gather_fn = jax.jit(kv_gather_blocks)

            def kv_scatter_blocks(k_pools, v_pools, idx, k_vals, v_vals):
                """Host-tier restore scatter (swap-in / spill promote):
                write staged host block copies back into pool blocks
                ``idx``. The destinations are freshly allocated private
                blocks — the write fence guarantees no in-flight
                dispatch targets them (fenced blocks never reach the
                free heap), so the restore cannot race a pipelined
                writer."""
                def s(p, vals):
                    return p.at[idx].set(vals.astype(p.dtype))
                return (_pin_kv(jax.tree_util.tree_map(
                            s, list(k_pools), list(k_vals))),
                        _pin_kv(jax.tree_util.tree_map(
                            s, list(v_pools), list(v_vals))))

            self._kv_scatter_fn = jax.jit(kv_scatter_blocks,
                                          donate_argnums=(0, 1))

        def set_tokens(tokens_buf, row, slot):
            return jax.lax.dynamic_update_slice(
                tokens_buf, row[None].astype(jnp.int32),
                (slot, jnp.int32(0)))

        def set_len(lens, slot, val):
            return jax.lax.dynamic_update_slice(lens, val[None], (slot,))

        def set_pooled_zero(pooled, slot):
            z = jnp.zeros((1, pooled.shape[1]), pooled.dtype)
            return jax.lax.dynamic_update_slice(pooled, z,
                                                (slot, jnp.int32(0)))

        # NOT donated: an in-flight PendingStep may still hold this very
        # array as its pooled output (step_finish reads it after the
        # sync) — the zero-row update copies a tiny [B, H] buffer
        self._set_pooled_fn = jax.jit(set_pooled_zero)
        self._step_fn = jax.jit(step, donate_argnums=(1, 2, 3))
        # the paged step IS the unified step with `tables` bound — one
        # traced body serves both cache backends
        self._step_paged_fn = self._step_fn
        # same trick for the fused mixed step: one traced body, the
        # `tables` arg selects dense ChunkKVCache vs PagedKVCache
        self._fused_fn = jax.jit(fused_step, donate_argnums=(1, 2, 3))
        self._spec_fn = jax.jit(spec_step, donate_argnums=(1, 2, 3, 12))
        self._prefill_fn = jax.jit(prefill_chunk, donate_argnums=(1, 2))
        self._set_logits_fn = jax.jit(set_logits, donate_argnums=(0,))
        self._set_tokens_fn = jax.jit(set_tokens, donate_argnums=(0,))
        self._set_len_fn = jax.jit(set_len, donate_argnums=(0,))

    def _multi_fn(self, stride):
        """The compiled multi-step decode program for ``stride`` — one
        program per distinct effective stride (engine stride plus any
        smaller per-request pins actually seen), cached for the engine's
        lifetime (reset() keeps them: same shapes, same shardings)."""
        fn = self._multi_fns.get(stride)
        if fn is None:
            self._programs()
            fn = self._multi_fns[stride] = jax.jit(
                self._multi_step_factory(stride), donate_argnums=(1, 2, 3))
        return fn

    def _multi_spec_fn(self, stride):
        """The compiled multi-window SPECULATIVE decode program for
        ``stride`` windows per dispatch — cached per distinct stride for
        the engine's lifetime, exactly like :meth:`_multi_fn`."""
        fn = self._multi_spec_fns.get(stride)
        if fn is None:
            self._programs()
            fn = self._multi_spec_fns[stride] = jax.jit(
                self._multi_spec_factory(stride),
                donate_argnums=(1, 2, 3, 14))
        return fn

    # ------------------------------------------------------------------
    # acceptance-adaptive verify-k (fused speculative scheduling)
    # ------------------------------------------------------------------
    def _spec_k_for(self, slot):
        """Draft count of ``slot``'s next verify grant: its acceptance
        EWMA scaled into [1, speculative_k - 1] (optimistic full window
        until the first readout teaches otherwise). A low-acceptance
        request keeps proposing ONE draft — never zero, so the EWMA can
        recover when the stream turns repetitive again — instead of
        burning the step budget on windows that roll back."""
        ewma = slot.req.spec_ewma
        if ewma is None:
            ewma = self._spec_ewma.get(slot.req.request_id, 1.0)
        kd = self.speculative_k - 1
        return max(1, min(kd, int(round(ewma * kd + 0.25))))

    def _update_spec_ewma(self, slot, proposed, accepted):
        """Fold one readout's accepted/proposed draft counts into the
        request's acceptance EWMA (request field + the engine's
        rid-keyed mirror, which survives reset() for restart
        resumption)."""
        if proposed <= 0:
            return
        rate = accepted / proposed
        prev = slot.req.spec_ewma
        if prev is None:
            prev = self._spec_ewma.get(slot.req.request_id, rate)
        ewma = (1.0 - _SPEC_EWMA_ALPHA) * prev + _SPEC_EWMA_ALPHA * rate
        slot.req.spec_ewma = ewma
        self._spec_ewma[slot.req.request_id] = ewma

    def spec_ewma_for(self, request_id):
        """READ-ONLY: the persisted draft-acceptance EWMA of
        ``request_id`` (None = never speculated) — what the replica
        router forwards on failover so the survivor's verify grants
        start at the learned window instead of the optimistic
        default."""
        return self._spec_ewma.get(request_id)

    def _effective_stride(self):
        """The readout stride the NEXT all-decode dispatch should run:
        the engine's ``readout_stride`` capped by every active slot's
        per-request pin (a latency-tier request pinning 1 drags the
        whole batch to per-step readout while it is resident — the
        documented tradeoff), and by ``horizon`` for engines that use
        the legacy scan amortization instead."""
        if self.scheduler != "fused" or self.readout_stride <= 1:
            return self.horizon
        pins = [s.req.readout_stride for s in self.slots
                if s is not None and s.req.readout_stride is not None]
        return max(1, min([self.readout_stride] + pins))

    # ------------------------------------------------------------------
    # batched multi-LoRA (tenant) plumbing — serving/adapters.py
    # ------------------------------------------------------------------
    def _lora_armed(self):
        return self.adapter_store is not None and \
            len(self.adapter_store) > 0

    def _ensure_adapter_cache(self):
        if self.adapter_cache is None:
            from ..serving.adapters import AdapterDeviceCache
            self.adapter_cache = AdapterDeviceCache(
                self.adapter_store, n_slots=self._adapter_slots,
                make_zeros=self._make_zeros)
        return self.adapter_cache

    def _lora_pack(self, rows):
        """The traced LoRA arguments of one dispatch: the device stacks
        plus the per-batch-row adapter slot vector ``rows`` ([B] int32;
        0 = base). None while no adapter is registered — the step
        programs then trace the exact pre-adapter body (bit-identical
        base serving); the first registered adapter flips the signature
        and retraces once."""
        if not self._lora_armed():
            return None
        cache = self._ensure_adapter_cache()
        return {"A": cache.A, "B": cache.B, "alpha": cache.alpha,
                "slots": np.asarray(rows, np.int32)}

    def _slot_adapter_rows(self):
        return np.array([s.a_slot if s is not None else 0
                         for s in self.slots], np.int32)

    def _acquire_adapter(self, req):
        """Pin ``req``'s adapter resident in the device cache; returns
        its device row (0 = base), or None when every cache slot is
        pinned by resident requests — the admission then DEFERS exactly
        like a dry KV pool (a retirement releases a slot)."""
        aid = getattr(req, "adapter_id", 0)
        if not aid:
            return 0
        cache = self._ensure_adapter_cache()
        before = dict(cache.stats)
        row = cache.acquire(aid)
        self.stats["adapter_cache_hits"] += \
            cache.stats["hits"] - before["hits"]
        self.stats["adapter_cache_misses"] += \
            cache.stats["misses"] - before["misses"]
        self.stats["adapter_swaps"] += \
            cache.stats["swaps"] - before["swaps"]
        return row

    def _release_adapter(self, adapter_id):
        if adapter_id and self.adapter_cache is not None:
            self.adapter_cache.release(adapter_id)

    def adapter_resident(self, adapter_id):
        """READ-ONLY: could a request for ``adapter_id`` admit without a
        swap right now? The replica router's adapter-affinity probe
        (dict reads only — safe from any thread)."""
        if not adapter_id:
            return True
        return self.adapter_cache is not None and \
            self.adapter_cache.resident(adapter_id)

    @staticmethod
    def _tenant_root(adapter_id):
        """The prefix-cache hash-chain ROOT of one tenant: adapter id 0
        keeps the historical root (base-tenant hashes are unchanged);
        any other id mixes into the seed, so two tenants' chains over
        the SAME prompt never collide — different fine-tunes produce
        different KV for identical tokens, and a shared block would
        silently serve tenant A's KV to tenant B."""
        if not adapter_id:
            return _ROOT_HASH
        return _ROOT_HASH + b"/tenant=" + str(int(adapter_id)).encode()

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=64, temperature=0.0,
                    top_p=1.0, eos_token_id=None, request_id=None,
                    committed_tokens=None, readout_stride=None,
                    adapter_id=0, kind="generate", spec_ewma=None,
                    export_kv=False, trace_ctx=None):
        """``readout_stride``: per-request latency-tier pin — cap the
        multi-step decode stride of every all-decode step this request
        is active in (1 = sync the host every step; None = the engine
        default; ignored unless the engine runs ``readout_stride > 1``).

        ``committed_tokens``: tokens ALREADY generated for this request
        in a previous life (supervised-restart / failover re-admission).
        They join the prompt for prefill — exactly the pool-pressure
        preemption stitch — so the engine's stream CONTINUES: only new
        tokens hit the stream callback, the returned output prepends the
        committed ones, and ``max_new_tokens`` counts only NEW tokens.
        Token-exactness rides the per-(rid, position) fold_in sampling
        keys: position ``len(prompt)+len(committed)`` samples the same
        token it would have in the uninterrupted run.

        ``adapter_id``: the request's TENANT — a registered id in the
        engine's adapter store (0 = base model). ``kind="embed"`` makes
        the request PREFILL-ONLY (fused scheduler required): no decode
        tokens, no sampling; the finished RequestOutput carries the
        mean-pooled final hidden state in ``embedding``.

        ``export_kv``: stage the request's committed KV as a staged
        export entry at its finish (disaggregated serving — see
        :meth:`export_kv`)."""
        ids = np.asarray(
            prompt_ids.numpy() if hasattr(prompt_ids, "numpy")
            else prompt_ids, dtype=np.int32).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if readout_stride is not None and int(readout_stride) < 1:
            raise ValueError(f"readout_stride must be >= 1, got "
                             f"{readout_stride}")
        adapter_id = int(adapter_id or 0)
        if adapter_id:
            if self.adapter_store is None:
                raise ValueError(
                    f"adapter_id {adapter_id} on an engine without an "
                    f"adapter_store (LLMEngine(adapter_store=...))")
            if not self.adapter_store.has(adapter_id):
                raise ValueError(f"unknown adapter_id {adapter_id} (not "
                                 f"registered in the adapter store)")
        if kind not in ("generate", "embed"):
            raise ValueError(f"unknown request kind {kind!r}")
        if kind == "embed":
            if self.scheduler != "fused":
                raise ValueError(
                    "embedding (prefill-only) requests need "
                    "scheduler='fused' — the prefill-only grant kind "
                    "lives in the fused token-budget walk")
            max_new_tokens = 0
            # no decode headroom needed: an embed prompt may run to
            # capacity - 1 (the +1 in the fused pool arithmetic covers
            # the last granted position)
            if len(ids) > self.capacity - 1:
                raise ValueError(
                    f"embedding prompt of {len(ids)} tokens exceeds the "
                    f"engine capacity ({self.capacity} - 1)")
            self.stats["embed_requests"] += 1
        committed = [int(t) for t in committed_tokens] \
            if committed_tokens else []
        if committed:
            ids = np.concatenate(
                [ids, np.asarray(committed, np.int32)])
        if kind != "embed" and \
                len(ids) >= self.capacity - self.speculative_k:
            raise ValueError(f"prompt of {len(ids)} tokens leaves no room "
                             f"to generate (engine capacity "
                             f"{self.capacity})")
        rid = self._next_id if request_id is None else request_id
        if request_id is not None and (
                rid in self.finished_outputs
                or any(r.request_id == rid for r in self.waiting)
                or any(s is not None and s.req.request_id == rid
                       for s in self.slots)):
            raise ValueError(f"duplicate request_id {rid!r}")
        self._next_id = max(self._next_id, rid) + 1
        if committed:
            # the preemption stitch: _finish_tokens pops this and
            # prepends it to whatever the slot generates from here on
            self._preempted_prefix[rid] = \
                self._preempted_prefix.pop(rid, []) + committed
        self.waiting.append(GenerationRequest(
            rid, ids, int(max_new_tokens), float(temperature), float(top_p),
            eos_token_id,
            readout_stride=(int(readout_stride)
                            if readout_stride is not None else None),
            adapter_id=adapter_id, kind=kind,
            # acceptance-adaptive verify-k seed: an explicit carry-over
            # (router failover) wins; else the engine's rid-keyed mirror
            # (supervised restart / preemption re-admission under the
            # same rid) — fresh requests start at the optimistic default
            spec_ewma=(float(spec_ewma) if spec_ewma is not None
                       else self._spec_ewma.get(rid)),
            export_kv=bool(export_kv), trace_ctx=trace_ctx))
        if trace_ctx is not None:
            rec = self._rec()
            if rec is not None:
                # direct-engine admissions stamp the timeline here; the
                # server's submit() already stamped its own recorder
                # (set_trace_ctx is idempotent for the same context)
                rec.set_trace_ctx(rid, trace_ctx if isinstance(
                    trace_ctx, dict) else trace_ctx.to_dict())
        return rid

    def has_unfinished(self):
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def cancel(self, request_id, reason="cancelled"):
        """Cancel a waiting or running request. Returns the partial
        RequestOutput (finish_reason ``reason``, default 'cancelled' —
        the serving layer passes 'deadline' for expiries), or None if the
        id is unknown/already finished. A cancelled running slot frees
        immediately (slot and, under paged KV, its pool blocks); its KV
        region is simply reused by the next admission."""
        for i, req in enumerate(self.waiting):
            if req.request_id == request_id:
                del self.waiting[i]
                out = RequestOutput(
                    request_id, self._finish_tokens(req, []), True,
                    reason)
                self.finished_outputs[request_id] = out
                return out
        for b, slot in enumerate(self.slots):
            if slot is not None and slot.req.request_id == request_id:
                out = RequestOutput(
                    request_id,
                    self._finish_tokens(slot.req, slot.generated), True,
                    reason)
                self.finished_outputs[request_id] = out
                self._free_slot(b)
                return out
        return None

    # ------------------------------------------------------------------
    # paged-pool allocator (host side; tables are a traced step input)
    # ------------------------------------------------------------------
    def _n_allocatable(self):
        """Blocks a new allocation may claim: strictly free ones plus the
        LRU-cached pool (refcount-0 registered content, evictable). Pool
        pressure consumes BOTH before any live slot is preempted."""
        return len(self._free_blocks) + len(self._lru)

    def _pop_block(self):
        """One writable physical block: the smallest FREE index first
        (order-stable layout), else evict the oldest LRU-cached block —
        its content identity unregisters and the block is plain free."""
        if self._free_blocks:
            return heapq.heappop(self._free_blocks)
        phys, _ = self._lru.popitem(last=False)
        if self.kv_host_spill_bytes:
            # demote the evicted content to the host spill store BEFORE
            # its identity unregisters — a later probe promotes it back
            # instead of recomputing the chunk
            self._spill_block(phys)
        self._unregister(phys)
        self.stats["prefix_evicted_blocks"] += 1
        return phys

    def _alloc_blocks(self, slot_idx, n):
        """Grow slot `slot_idx` by `n` PRIVATE physical blocks (refcount
        1, content unregistered). False = pool dry (free + cached both
        exhausted)."""
        # owner check FIRST: the capacity probe itself reads allocator
        # state racily, so an off-thread attempt must be flagged even
        # when it would have failed the capacity check anyway
        self._assert_pool_owner("_alloc_blocks")
        if self._n_allocatable() < n:
            return False
        blocks = self._slot_blocks[slot_idx]
        for _ in range(n):
            phys = self._pop_block()
            self._block_ref[phys] = 1
            self._tables[slot_idx, len(blocks)] = phys
            blocks.append(phys)
        self._check_pool_invariants()
        return True

    def _release_block(self, phys):
        """Drop one reference. At refcount 0 the FENCE is authoritative:
        a block still under an in-flight write fence parks in quarantine
        — never in a pool the allocation ladder hands out from — until
        the dispatch that may still write it lands (``_unfence`` then
        routes it to the LRU if registered, the free heap otherwise).
        Registered blocks CAN be fenced: a mixed-step prefill grant
        publishes its just-filled blocks at dispatch time
        (``_register_upto``), so the grant's own write fence and the
        registration overlap until that step's finish. An unfenced
        registered block parks straight in the LRU cached pool (content
        stays probe-able); anything else returns to the free heap."""
        self._assert_pool_owner("_release_block")
        self._block_ref[phys] -= 1
        if self._block_ref[phys] > 0:
            return
        if self._write_fence.get(phys):
            self._quarantine.add(phys)
        else:
            self._park_free_block(phys)

    def _park_free_block(self, phys):
        """Route an unfenced refcount-0 block to the pool its
        registration state earns — THE one copy of the rule, shared by
        direct release and the quarantine drain: LRU cached pool if its
        content is published (probe-able), free heap otherwise."""
        if phys in self._block_hash:
            self._lru[phys] = None
        else:
            heapq.heappush(self._free_blocks, phys)

    # ---- stride-aware in-flight write fence ---------------------------
    def _fence_blocks(self, b, lo, hi, fenced):
        """Fence every block of slot ``b`` covering positions [lo, hi]:
        the dispatch being built may write them, so until its
        step_finish they must not be handed to a new owner. Fencing is
        CONSERVATIVE — ``lo`` is the slot's committed length (not its
        scheduled one), so even a dispatch whose predecessor early-exits
        in-graph below its scheduled growth (pool-budget clamp) writes
        only fenced blocks."""
        bs = self.block_size
        blocks = self._slot_blocks[b]
        for blk in range(lo // bs, min(hi // bs + 1, len(blocks))):
            phys = blocks[blk]
            self._write_fence[phys] = self._write_fence.get(phys, 0) + 1
            fenced.append(phys)

    def _unfence(self, fenced):
        """Drop one fence per listed block (its dispatch's device work —
        including every KV write — provably landed: the token sync
        completed). A quarantined block whose last fence drops leaves
        quarantine for the pool its registration state earns: the LRU
        cached pool if its content is published (probe-able again), the
        free heap otherwise."""
        self._assert_pool_owner("_unfence")
        for phys in fenced:
            n = self._write_fence.get(phys, 0) - 1
            if n > 0:
                self._write_fence[phys] = n
            else:
                self._write_fence.pop(phys, None)
                if phys in self._quarantine:
                    self._quarantine.discard(phys)
                    self._park_free_block(phys)
        if fenced:
            self._check_pool_invariants()

    # ---- content-addressed store (enable_prefix_cache) ---------------
    def _chain_hash(self, parent, tokens):
        """Rolling prefix hash of one full block: blake2b over the parent
        chain hash + the block's token ids. Chaining makes equal PREFIXES
        (not merely equal blocks) collide on purpose, and the digest is
        deterministic across runs so traces diff cleanly."""
        return hashlib.blake2b(
            parent + np.asarray(tokens, np.int32).tobytes(),
            digest_size=16).digest()

    def _register_block(self, phys, chain_hash, parent, tokens):
        """Publish a FULL private block's content identity. First writer
        wins: if the store already has this chain hash (another block
        with identical prefix content), ours stays unregistered and will
        free normally — one canonical block per content."""
        if chain_hash in self._store or phys in self._block_hash:
            return
        self._assert_pool_owner("_register_block")
        self._store[chain_hash] = phys
        self._block_hash[phys] = chain_hash
        self._block_parent[phys] = parent
        self._block_tokens[phys] = np.asarray(tokens, np.int32).tobytes()
        self._children.setdefault(parent, []).append(phys)

    def _unregister(self, phys):
        self._assert_pool_owner("_unregister")
        h = self._block_hash.pop(phys, None)
        if h is None:
            return
        self._store.pop(h, None)
        parent = self._block_parent.pop(phys)
        kids = self._children.get(parent)
        if kids is not None:
            kids.remove(phys)
            if not kids:
                del self._children[parent]
        self._block_tokens.pop(phys, None)

    def _slot_token_range(self, slot, lo, hi):
        """Token ids at positions [lo, hi) of ``slot``'s committed stream
        (prompt, then generated)."""
        P = slot.prompt_len
        if hi <= P:
            return slot.req.prompt_ids[lo:hi]
        gen = np.asarray(slot.generated, np.int32)
        if lo >= P:
            return gen[lo - P:hi - P]
        return np.concatenate([slot.req.prompt_ids[lo:], gen[:hi - P]])

    def _register_upto(self, slot_idx, slot, upto_pos):
        """Register every newly FULL block of ``slot``'s committed stream
        [0, upto_pos) in the content store, extending its hash chain.
        Shared/hit blocks were registered by their first writer and are
        skipped via ``reg_blocks``; the COW tail registers here once the
        slot's own appends fill it."""
        if not self.prefix_cache:
            return
        bs = self.block_size
        blocks = self._slot_blocks[slot_idx]
        n_full = min(upto_pos // bs, len(blocks))
        while slot.reg_blocks < n_full:
            i = slot.reg_blocks
            toks = self._slot_token_range(slot, i * bs, (i + 1) * bs)
            parent = slot.chain[i - 1] if i else \
                self._tenant_root(slot.req.adapter_id)
            h = self._chain_hash(parent, toks)
            slot.chain.append(h)
            self._register_block(blocks[i], h, parent, toks)
            slot.reg_blocks += 1

    def _probe_prefix(self, slot_idx, token_ids, chunk_granular=False,
                      adapter_id=0, no_cow=False):
        """Find the longest cached prefix of ``token_ids`` and attach it
        to slot ``slot_idx``: pure table writes + refcount bumps, zero
        prefill FLOPs for the hit span. The hit is capped at P-1 tokens —
        at least the final prompt position always recomputes so admission
        still produces the last-position logits the sampler needs.

        ``chunk_granular`` (legacy scheduler): the hit rounds DOWN to a
        whole number of prefill chunks, because legacy chunk windows
        scatter whole chunk spans and must never scatter into a shared
        block. The fused scheduler drop-scatters exact positions, so it
        keeps block granularity and additionally extends the hit to
        TOKEN granularity through a copy-on-write tail.

        Returns ``(hit_tokens, chain)`` where ``chain`` is the list of
        chain hashes of the full-block hits."""
        P = len(token_ids)
        bs = self.block_size
        max_full = (P - 1) // bs
        if chunk_granular:
            max_full = ((P - 1) // self.chunk) * (self.chunk // bs)
        # the chain seeds at the TENANT root: two adapters' chains over
        # the same prompt diverge from block 0, so no probe can ever
        # attach another tenant's KV
        found, parent = [], self._tenant_root(adapter_id)
        for k in range(min(max_full, self._max_blocks)):
            h = self._chain_hash(parent, token_ids[k * bs:(k + 1) * bs])
            phys = self._store.get(h)
            if phys is None and self.kv_host_spill_bytes:
                # device miss, host-tier hit: promote the spilled block
                # back into the pool (re-registered) so the walk treats
                # it like any cached hit
                phys = self._promote_spilled(h)
            if phys is None:
                break
            # CLAIM the block the moment it is found — not in a second
            # pass. A LATER iteration's spill promotion allocates
            # (_pop_block), and the LRU eviction inside it would
            # happily hand out a refcount-0 block this walk already
            # found, overwriting content we are about to attach. A
            # registered block may also sit in QUARANTINE instead of
            # the LRU (released while its publishing grant's dispatch
            # was still in flight); attaching it is safe — the
            # in-flight write IS the registered content and precedes
            # any reader dispatch in program order — but it must leave
            # quarantine or its unfence would free a live block.
            if self._block_ref[phys] == 0:
                self._lru.pop(phys, None)
                self._quarantine.discard(phys)
            self._block_ref[phys] += 1
            found.append((h, phys))
            parent = h
        if chunk_granular:
            # the hit boundary must be a chunk-window boundary: roll the
            # claim back on the trimmed tail (registered blocks re-park
            # in the LRU, probe-able again)
            per = self.chunk // bs
            keep = (len(found) // per) * per
            for h, phys in found[keep:]:
                self._release_block(phys)
            found = found[:keep]
        blocks = self._slot_blocks[slot_idx]
        chain = []
        for k, (h, phys) in enumerate(found):
            self._tables[slot_idx, k] = phys
            blocks.append(phys)
            chain.append(h)
        hit = len(found) * bs
        if not chunk_granular and not no_cow:
            # no_cow (swap-in re-admission): the hit must stay
            # BLOCK-aligned — the restore attaches whole host block
            # copies after it, which a token-granular COW tail would
            # misalign (and the swap entry covers that span anyway)
            hit += self._cow_tail(slot_idx, token_ids, hit, chain,
                                  adapter_id=adapter_id)
        self._check_pool_invariants()
        return hit, chain

    def prefix_chain_hashes(self, token_ids, adapter_id=0):
        """Per-full-block rolling chain hashes of ``token_ids`` — the
        router's affinity precompute. Content-only (no engine state
        read), so one computation serves every replica with the same
        ``block_size`` AND tenant (the chain seeds at the tenant root).
        Empty when the prefix cache is off."""
        if self.cache_impl != "paged" or not self.prefix_cache:
            return []
        ids = np.asarray(token_ids, np.int32).reshape(-1)
        bs = self.block_size
        parent, out = self._tenant_root(adapter_id), []
        for k in range(min((len(ids) - 1) // bs, self._max_blocks)):
            parent = self._chain_hash(parent, ids[k * bs:(k + 1) * bs])
            out.append(parent)
        return out

    def probe_prefix_len(self, token_ids, chain_hashes=None, adapter_id=0):
        """READ-ONLY affinity probe: how many leading tokens of
        ``token_ids`` the content store could serve right now (full
        cached blocks only — no COW extension, no refcount bumps, no
        table writes). The replica router calls this from ITS thread to
        score placements; the walk is dict membership tests only, which
        the GIL makes atomic per op — a store mutating concurrently can
        make the answer stale, never wrong-shaped, and the real attach
        re-probes under the engine thread. Hashing is TP-oblivious: the
        store keys on token content, not on shard layout. Pass
        ``chain_hashes`` (from :meth:`prefix_chain_hashes`) to skip
        re-hashing the prompt per probe. Returns 0 when the prefix
        cache is off."""
        if self.cache_impl != "paged" or not self.prefix_cache:
            return 0
        if chain_hashes is None:
            chain_hashes = self.prefix_chain_hashes(token_ids,
                                                    adapter_id=adapter_id)
        hit = 0
        for h in chain_hashes[:self._max_blocks]:
            # the host spill store counts: a spilled block is one H2D
            # promote away from serving, far cheaper than the recompute
            # the affinity score is steering around
            if h not in self._store and h not in self._spill:
                break
            hit += self.block_size
        return hit

    def _cow_tail(self, slot_idx, token_ids, hit, chain, adapter_id=0):
        """Token-granular hit extension (copy-on-write): if a cached full
        block CONTINUES the hit chain and its leading tokens match the
        remaining prompt, the slot needs exactly that block's prefix —
        but must then append its own tokens into it, and the source is
        content other requests may still reference. So the source block
        is cloned device-side into a fresh PRIVATE block (the partial
        tail is always private) and the matched span's prefill is
        skipped too. Returns the extra tokens hit (0 = no match / pool
        dry)."""
        P = len(token_ids)
        bs = self.block_size
        cap = min(bs - 1, P - 1 - hit)
        if cap <= 0:
            return 0
        parent = chain[-1] if chain else self._tenant_root(adapter_id)
        rem = np.asarray(token_ids[hit:hit + cap], np.int32)
        best, best_t = None, 0
        for phys in self._children.get(parent, ()):
            cand = np.frombuffer(self._block_tokens[phys],
                                 np.int32)[:len(rem)]
            t = int(np.cumprod(cand == rem).sum())
            if t > best_t:
                best, best_t = phys, t
        if best is None or not self._alloc_blocks(slot_idx, 1):
            return 0
        dst = self._slot_blocks[slot_idx][-1]
        # the copy dispatches NOW: even if allocating dst just evicted
        # `best` from the store, its device content is only overwritten
        # by LATER dispatches — program order over the shared pool
        # buffers makes the clone read the original bytes
        self._k, self._v = self._cow_fn(self._k, self._v,
                                        np.int32(best), np.int32(dst))
        self.stats["prefix_cow_blocks"] += 1
        return best_t

    # ---- host KV tier (kv_host_swap / kv_host_spill_bytes) ------------
    # The fence-tracked swap API: every device<->host KV-pool copy in
    # the engine goes through the four functions below (the PTL006
    # checker in paddle_tpu.analysis enforces exactly that). Copies are
    # ASYNC — the gather/scatter dispatches here, the transfer overlaps
    # the step's device work in the step_begin/step_finish gap, and
    # step_finish (or a consumer that needs the bytes sooner)
    # materializes them.

    def _pad_block_idx(self, blocks):
        """Block-index vector padded to the next power-of-two length
        with the trailing SCRATCH block (index n_blocks — never handed
        out, routinely garbage-written by the kernels), so the compiled
        gather/scatter programs retrace O(log max_blocks) times total
        instead of once per distinct block count."""
        n = len(blocks)
        m = 1 << max(n - 1, 0).bit_length()
        idx = np.full((max(m, 1),), self.n_blocks, np.int32)
        idx[:n] = blocks
        return idx

    def _swap_out_slot(self, b, slot):
        """Demote slot ``b``'s committed KV to host RAM at preemption
        (the tier's swap-out half). The gather's input is the newest
        pool futures, so in-flight pipelined writers need no special
        handling: their writes land at positions >= the committed
        length, and the gather is sequenced after them by data flow —
        the fence/quarantine then keeps the released blocks from being
        handed to a new owner while those writers are still outstanding,
        exactly as for any other release."""
        req = slot.req
        kv_len = slot.prefill_pos + len(slot.generated)
        if kv_len <= 0 or req.kind == "embed":
            # an embed slot's pooled accumulator cannot survive a skip
            # of its prefill span (same reason embeds never probe the
            # prefix cache) — let it re-prefill
            return
        nb = (kv_len - 1) // self.block_size + 1
        blocks = self._slot_blocks[b][:nb]
        if len(blocks) < nb:
            return
        t0 = time.perf_counter()
        k_host, v_host = self._kv_gather_fn(self._k, self._v,
                                            self._pad_block_idx(blocks))
        for leaf in jax.tree_util.tree_leaves([k_host, v_host]):
            try:
                leaf.copy_to_host_async()
            except AttributeError:      # CPU fallback: a buffer move
                pass
        done = np.concatenate([req.prompt_ids,
                               np.asarray(slot.generated, np.int32)])
        entry = {"tokens": done[:kv_len], "adapter_id": req.adapter_id,
                 "n_blocks": nb, "k": k_host, "v": v_host, "ready": False,
                 "nbytes": nb * self.kv_bytes_per_block()}
        # a re-preempted request's newest committed state wins
        self._swap_store[req.request_id] = entry
        self._swap_pending.append(entry)
        self.stats["kv_swap_out_blocks"] += nb
        self.stats["kv_swap_out_bytes"] += entry["nbytes"]
        self.stats["swap_out_time_s"] += time.perf_counter() - t0

    def _drain_swap_writes(self):
        """Materialize every pending device→host tier copy into plain
        numpy and drop the device references — called in the
        step_begin/step_finish gap's finish side (the transfer already
        overlapped the step's device work) and lazily by any consumer
        that needs an entry sooner."""
        if not self._swap_pending:
            return
        t0 = time.perf_counter()
        for entry in self._swap_pending:
            nb = entry["n_blocks"]
            entry["k"] = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:nb], entry["k"])
            entry["v"] = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:nb], entry["v"])
            entry["ready"] = True
        self._swap_pending.clear()
        self.stats["swap_out_time_s"] += time.perf_counter() - t0

    def _try_swap_restores(self):
        """The swap-in half, run at the top of every MIXED step (the
        restore fires exactly where the prefill grants it replaces
        would have been scheduled): every ramping slot with a live swap
        entry restores as many of its host-resident blocks as the pool
        can cover right now — async H2D scatter into private blocks,
        ``prefill_pos``/lens jump to the stitch. The entry SURVIVES a
        dry pool (restores retry as retirements free blocks — the whole
        point of demoting instead of discarding) and partial restores
        stay BLOCK-ALIGNED so the remainder can restore later; it is
        consumed when the stitch reaches ``T-1`` (the final position
        recomputes — deterministically identical KV — so the last
        prefill grant still produces the sampler's logits), and dropped
        when it can no longer apply (tenant/token drift, the ramp
        passed it by re-prefilling, or a misaligned budget-clamped
        grant boundary)."""
        # gate on the STORE, not kv_host_swap: shipped entries (a peer's
        # import_kv) restore through this same path on engines that never
        # enabled local preempt-swap
        if self.cache_impl != "paged" or not self._swap_store:
            return
        bs = self.block_size
        for b, slot in enumerate(self.slots):
            if slot is None:
                continue
            rid = slot.req.request_id
            entry = self._swap_store.get(rid)
            if entry is None:
                continue
            req = slot.req
            T = len(entry["tokens"])
            pos = slot.prefill_pos
            # the token-prefix compare is O(T): run it ONCE per
            # (entry, resident request) — both arrays are immutable, so
            # the cached verdict holds for every dry-pool retry
            if entry.get("validated") != rid:
                if entry["adapter_id"] != req.adapter_id or \
                        T > slot.prompt_len or \
                        not np.array_equal(entry["tokens"],
                                           req.prompt_ids[:T]):
                    del self._swap_store[rid]
                    continue
                entry["validated"] = rid
            if (not slot.ramping) or slot.generated or pos >= T - 1 or \
                    req.kind == "embed":
                del self._swap_store[rid]
                continue
            if pos % bs:
                # a budget-clamped grant left the ramp mid-block: keep
                # the entry (a later aligned position may restore; the
                # finish/preempt paths clean it up regardless)
                continue
            target = T - 1               # the stitch cap: T-1 recomputes
            first_blk = pos // bs
            n_restore = target // bs + 1 - first_blk
            t0 = time.perf_counter()
            # blocks the slot already owns past the stitch count toward
            # the restore span (a budget-clamped grant may have grabbed
            # coverage it never filled) — only the shortfall allocates
            have = max(len(self._slot_blocks[b]) - first_blk, 0)
            got = min(n_restore, have + self._n_allocatable())
            need = first_blk + got - len(self._slot_blocks[b])
            if got <= 0 or (need > 0 and
                            not self._alloc_blocks(b, need)):
                continue                 # pool dry NOW — retry next step
            self._drain_swap_writes()    # the entry may still be staging
            dst = self._slot_blocks[b][first_blk:first_blk + got]
            idx = self._pad_block_idx(dst)
            m = len(idx)

            def staged(x):
                rows = x[first_blk:first_blk + got]
                if m > got:
                    pad = np.zeros((m - got,) + rows.shape[1:],
                                   rows.dtype)
                    rows = np.concatenate([rows, pad])
                return rows

            self._k, self._v = self._kv_scatter_fn(
                self._k, self._v, idx,
                jax.tree_util.tree_map(staged, entry["k"]),
                jax.tree_util.tree_map(staged, entry["v"]))
            covered = (first_blk + got) * bs
            # a partial restore stops at a BLOCK boundary (the remainder
            # restores or re-prefills later); a full one stitches at T-1
            stitch = target if covered > target else covered
            slot.prefill_pos = stitch
            self._lens = self._set_len_fn(self._lens, np.int32(b),
                                          np.int32(stitch))
            if stitch >= target:
                del self._swap_store[rid]
            shipped = bool(entry.get("shipped"))
            if shipped:
                # cross-replica ships book their OWN counters so the
                # StepRecord swap-byte deltas stay the preempt_swap
                # classifier's exclusive signal (see _spill_block note)
                self.stats["kv_ship_in_blocks"] += got
                self.stats["kv_ship_in_bytes"] += got * \
                    self.kv_bytes_per_block()
            else:
                self.stats["kv_swap_in_blocks"] += got
                self.stats["kv_swap_in_bytes"] += got * \
                    self.kv_bytes_per_block()
            self.stats["kv_swap_saved_tokens"] += max(stitch - pos, 0)
            restore_s = time.perf_counter() - t0
            self.stats["swap_in_time_s"] += restore_s
            if shipped:
                # the migration's STITCH phase wall (alloc + H2D scatter
                # + lens jump), keyed by rid for the router's migration
                # phase breakdown (ReplicaRouter reads it after the
                # decode leg resolves; bounded by _swap_store churn)
                self._stitch_s[rid] = \
                    self._stitch_s.get(rid, 0.0) + restore_s
            rec = self._rec()
            if rec is not None:
                rec.req_event(rid,
                              "kv_shipped_in" if shipped else "swapped_in",
                              step_id=rec.next_step_id(),
                              value=max(stitch - pos, 0))
                if shipped:
                    # a dedicated stitch span so the merged cross-replica
                    # trace shows the restore wall as its own sub-span
                    rec.req_event(rid, "kv_stitch",
                                  step_id=rec.next_step_id(),
                                  value=round(restore_s, 6))

    def _spill_block(self, phys):
        """Demote an LRU-evicted registered block's content to the
        bounded host spill store (the tier's eviction half), keyed by
        its chain hash so a later content-store probe can promote it
        back instead of recomputing the chunk. Called BEFORE
        ``_unregister`` strips the block's identity; the byte budget
        evicts the oldest spilled entries first."""
        h = self._block_hash.get(phys)
        per = self.kv_bytes_per_block()
        if h is None or h in self._spill or per > self.kv_host_spill_bytes:
            return
        t0 = time.perf_counter()
        k_host, v_host = self._kv_gather_fn(self._k, self._v,
                                            self._pad_block_idx([phys]))
        for leaf in jax.tree_util.tree_leaves([k_host, v_host]):
            try:
                leaf.copy_to_host_async()
            except AttributeError:
                pass
        while self._spill_bytes + per > self.kv_host_spill_bytes \
                and self._spill:
            _, old = self._spill.popitem(last=False)
            self._spill_bytes -= old["nbytes"]
        entry = {"parent": self._block_parent[phys],
                 "tokens": self._block_tokens[phys],
                 "n_blocks": 1, "k": k_host, "v": v_host, "ready": False,
                 "nbytes": per}
        self._spill[h] = entry
        self._spill_bytes += per
        self._swap_pending.append(entry)
        # spill traffic books on its OWN counters (kv_spill_blocks /
        # kv_host_spill_blocks), never on kv_swap_out_bytes: the
        # StepRecord swap-byte deltas are the preempt_swap-vs-reprefill
        # classifier's signal, and spill bytes riding them would label a
        # swap-off preemption step "preempt_swap" whenever an unrelated
        # eviction landed on it
        self.stats["kv_spill_blocks"] += 1
        self.stats["swap_out_time_s"] += time.perf_counter() - t0

    def _promote_spilled(self, h):
        """Promote a spilled block back into the device pool: claim a
        writable block, scatter the host copy in, RE-REGISTER the
        content identity, and park it refcount-0 in the LRU — the
        probe's normal attach path then bumps it live, so promotion is
        invisible to everything above the content store. Returns the
        physical block, or None (spill miss / pool dry)."""
        entry = self._spill.get(h)
        if entry is None or not self._n_allocatable():
            return None
        t0 = time.perf_counter()
        self._drain_swap_writes()
        del self._spill[h]
        self._spill_bytes -= entry["nbytes"]
        phys = self._pop_block()
        idx = self._pad_block_idx([phys])

        def staged(x):
            if len(idx) > 1:
                pad = np.zeros((len(idx) - 1,) + x.shape[1:], x.dtype)
                return np.concatenate([x[:1], pad])
            return x[:1]

        self._k, self._v = self._kv_scatter_fn(
            self._k, self._v, idx,
            jax.tree_util.tree_map(staged, entry["k"]),
            jax.tree_util.tree_map(staged, entry["v"]))
        self._register_block(phys, h, entry["parent"],
                             np.frombuffer(entry["tokens"], np.int32))
        self._lru[phys] = None
        # promote traffic books on kv_promote_blocks only — see the
        # matching note in _spill_block (swap-byte deltas stay the
        # preemption classifier's exclusive signal)
        self.stats["kv_promote_blocks"] += 1
        self.stats["swap_in_time_s"] += time.perf_counter() - t0
        return phys

    def swap_resident_rids(self):
        """Request ids whose committed KV currently lives in the HOST
        tier (preempted + swapped out — awaiting re-admission, or
        re-admitted and mid-restore) — a READ-ONLY probe the replica
        router uses to know which of a replica's requests can resume
        from their streamed tokens without recompute on failover."""
        if self.cache_impl != "paged":
            return ()
        return tuple(self._swap_store)

    # ---- cross-replica KV shipping (disaggregated prefill/decode) -----
    # The staged-entry format is the PR-13 swap entry plus identity
    # (rid, chain hashes) and pool-geometry fields, so export/import
    # reuse the same gather/scatter programs and the same stitch-at-T-1
    # re-admission. serving/kv_transport.py serializes exactly these
    # dicts to bytes-on-wire.

    def _export_slot_kv(self, b, slot):
        """Stage slot ``b``'s committed KV as a SHIPPABLE export entry —
        runs on the engine thread at the finish site of an
        ``export_kv``-flagged request, while the slot's blocks are still
        allocated. Same gather + async D2H staging as ``_swap_out_slot``;
        the entry carries identity (rid, tenant, tokens, chain hashes)
        and pool geometry so the destination can validate before it
        scatters. Materialization is deferred to :meth:`export_kv` (the
        copy overlaps whatever the device is doing next)."""
        req = slot.req
        kv_len = slot.prefill_pos + len(slot.generated)
        if kv_len <= 0 or req.kind == "embed":
            return
        nb = (kv_len - 1) // self.block_size + 1
        blocks = self._slot_blocks[b][:nb]
        if len(blocks) < nb:
            return
        t0 = time.perf_counter()
        k_host, v_host = self._kv_gather_fn(self._k, self._v,
                                            self._pad_block_idx(blocks))
        for leaf in jax.tree_util.tree_leaves([k_host, v_host]):
            try:
                leaf.copy_to_host_async()
            except AttributeError:      # CPU fallback: a buffer move
                pass
        done = np.concatenate([req.prompt_ids,
                               np.asarray(slot.generated, np.int32)])
        entry = {"rid": req.request_id, "tokens": done[:kv_len],
                 "adapter_id": req.adapter_id, "n_blocks": nb,
                 "block_size": self.block_size, "kv_quant": self.kv_quant,
                 # chain hashes of the FULL blocks: the destination's
                 # content-store identity (its _register_upto recomputes
                 # and must agree) and the pull-on-miss address space
                 "chain": self.prefix_chain_hashes(
                     done[:kv_len], adapter_id=req.adapter_id),
                 "k": k_host, "v": v_host, "ready": False,
                 "nbytes": nb * self.kv_bytes_per_block()}
        self._export_store[req.request_id] = entry
        while len(self._export_store) > self._export_cap:
            self._export_store.popitem(last=False)
        self.stats["kv_ship_out_blocks"] += nb
        self.stats["kv_ship_out_bytes"] += entry["nbytes"]
        self.stats["swap_out_time_s"] += time.perf_counter() - t0

    def export_kv(self, request_id):
        """Pop + materialize the staged export entry for ``request_id``
        (an ``export_kv``-flagged request that finished on this engine).
        Callable from ANY thread — the pop is a GIL-atomic dict op and
        materialization only reads already-gathered host-bound staging
        arrays, never the pool. Returns the plain-numpy staged entry
        (serializable by ``serving.kv_transport``), or None."""
        if self.cache_impl != "paged":
            return None
        entry = self._export_store.pop(request_id, None)
        if entry is None:
            return None
        if not entry["ready"]:
            nb = entry["n_blocks"]
            entry["k"] = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:nb], entry["k"])
            entry["v"] = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:nb], entry["v"])
            entry["ready"] = True
        return entry

    def import_kv(self, entry):
        """Stage a SHIPPED entry for restore into this engine: validate
        pool-geometry compatibility, then seed the swap store under the
        entry's rid — the existing ``_try_swap_restores`` (engine
        thread) does the allocation, the fenced scatter, the one-token
        stitch and the identity validation (rid + tenant + token
        prefix) when the re-admitted request's slot next schedules.
        Callable from ANY thread (one GIL-atomic dict write). Returns
        True when staged, False on a compatibility reject — the router
        falls back to plain re-prefill."""
        if self.cache_impl != "paged" or self.scheduler != "fused":
            return False
        if not entry.get("ready") or entry.get("n_blocks", 0) <= 0:
            return False
        if int(entry.get("block_size", -1)) != self.block_size or \
                entry.get("kv_quant") != self.kv_quant:
            return False
        pool_leaves = jax.tree_util.tree_leaves([self._k, self._v])
        ent_leaves = jax.tree_util.tree_leaves(
            [entry["k"], entry["v"]])
        if len(ent_leaves) != len(pool_leaves):
            return False
        for p, e in zip(pool_leaves, ent_leaves):
            if tuple(e.shape[1:]) != tuple(p.shape[1:]) or \
                    np.dtype(e.dtype) != np.dtype(p.dtype):
                return False
        rid = entry["rid"]
        self._swap_store[rid] = {
            "tokens": np.asarray(entry["tokens"], np.int32),
            "adapter_id": int(entry["adapter_id"]),
            "n_blocks": int(entry["n_blocks"]),
            "k": entry["k"], "v": entry["v"], "ready": True,
            "nbytes": int(entry["n_blocks"]) * self.kv_bytes_per_block(),
            # shipped entries book kv_ship_in_* at restore, never the
            # kv_swap_* counters (the preempt classifier's signal)
            "shipped": True}
        return True

    def export_prefix_blocks(self, chain_hashes):
        """Pull-on-miss PEER export: package the registered prefix
        blocks for ``chain_hashes`` (device content store, or this
        engine's own spill store) as shippable single-block entries.
        READ-ONLY and callable from the router thread: the gather reads
        immutable pool array values through the dispatch lock, and the
        hash→phys mapping is re-checked AFTER materialization — a block
        evicted and reused mid-gather fails the re-check and is dropped
        (an eviction re-registered under the SAME hash is harmless by
        content addressing). Returns entries for the servable prefix
        only, stopping at the first miss."""
        out = []
        if self.cache_impl != "paged" or not self.prefix_cache:
            return out
        per = self.kv_bytes_per_block()
        for h in chain_hashes:
            phys = self._store.get(h)
            if phys is None:
                spilled = self._spill.get(h) \
                    if self.kv_host_spill_bytes else None
                if spilled is not None and spilled.get("ready"):
                    out.append({"hash": h, "parent": spilled["parent"],
                                "tokens": spilled["tokens"],
                                "n_blocks": 1,
                                "block_size": self.block_size,
                                "kv_quant": self.kv_quant,
                                "k": spilled["k"], "v": spilled["v"],
                                "ready": True,
                                "nbytes": spilled["nbytes"]})
                    continue
                break
            parent = self._block_parent.get(phys)
            tokens = self._block_tokens.get(phys)
            if parent is None or tokens is None:
                break
            with self._dispatch_lock:
                k_host, v_host = self._kv_gather_fn(
                    self._k, self._v, self._pad_block_idx([phys]))
            k_host = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:1], k_host)
            v_host = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:1], v_host)
            if self._store.get(h) != phys or \
                    self._block_hash.get(phys) != h:
                break       # evicted/reused mid-gather: stop the span
            out.append({"hash": h, "parent": parent, "tokens": tokens,
                        "n_blocks": 1, "block_size": self.block_size,
                        "kv_quant": self.kv_quant,
                        "k": k_host, "v": v_host, "ready": True,
                        "nbytes": per})
        if out:
            self.stats["kv_ship_out_blocks"] += len(out)
            self.stats["kv_ship_out_bytes"] += \
                sum(e["nbytes"] for e in out)
        return out

    def import_prefix_blocks(self, entries):
        """Queue shipped prefix-block entries (a peer's
        :meth:`export_prefix_blocks`) for this engine's spill store.
        Callable from ANY thread — entries land in a GIL-atomic inbox
        and the engine thread drains them (validated + budget-bounded)
        at the top of its next step, BEFORE admission probes run, so a
        request submitted right after the import hits them. Requires an
        armed spill store (``kv_host_spill_bytes > 0``); entries are
        dropped otherwise. Returns the number queued."""
        if self.cache_impl != "paged" or not self.prefix_cache or \
                not self.kv_host_spill_bytes:
            return 0
        n = 0
        for e in entries:
            if not e.get("ready") or \
                    int(e.get("block_size", -1)) != self.block_size or \
                    e.get("kv_quant") != self.kv_quant:
                continue
            self._spill_inbox.append(e)
            n += 1
        return n

    def _drain_spill_inbox(self):
        """Engine-thread half of pull-on-miss: move shipped prefix
        blocks from the inbox into the bounded spill store (hash
        re-derived from parent + tokens, so a corrupt or miskeyed entry
        can never register under a hash it doesn't hash to). The
        existing probe → ``_promote_spilled`` path then serves them
        exactly like locally spilled content."""
        if not self._spill_inbox:
            return
        inbox, self._spill_inbox = self._spill_inbox, []
        pool_leaves = jax.tree_util.tree_leaves([self._k, self._v])
        got_blocks = got_bytes = 0
        for e in inbox:
            tokens = np.frombuffer(e["tokens"], np.int32) \
                if isinstance(e["tokens"], bytes) \
                else np.asarray(e["tokens"], np.int32)
            h = self._chain_hash(e["parent"], tokens)
            if e.get("hash") is not None and e["hash"] != h:
                continue
            if h in self._store or h in self._spill:
                continue
            ent_leaves = jax.tree_util.tree_leaves([e["k"], e["v"]])
            if len(ent_leaves) != len(pool_leaves) or any(
                    tuple(x.shape[1:]) != tuple(p.shape[1:])
                    or np.dtype(x.dtype) != np.dtype(p.dtype)
                    for x, p in zip(ent_leaves, pool_leaves)):
                continue
            per = self.kv_bytes_per_block()
            if per > self.kv_host_spill_bytes:
                continue
            while self._spill_bytes + per > self.kv_host_spill_bytes \
                    and self._spill:
                _, old = self._spill.popitem(last=False)
                self._spill_bytes -= old["nbytes"]
            self._spill[h] = {"parent": e["parent"],
                              "tokens": tokens.tobytes(),
                              "n_blocks": 1, "k": e["k"], "v": e["v"],
                              "ready": True, "nbytes": per}
            self._spill_bytes += per
            got_blocks += 1
            got_bytes += per
        if got_blocks:
            self.stats["kv_ship_in_blocks"] += got_blocks
            self.stats["kv_ship_in_bytes"] += got_bytes

    def _check_pool_invariants(self):
        """Debug-only allocator audit (PADDLE_TPU_POOL_CHECKS=1; the test
        conftest enables it suite-wide): every physical block sits in
        exactly ONE of {free heap, LRU cached, live-refcounted}, their
        sizes sum to n_blocks (no leaks), refcounts equal table
        references, table rows mirror _slot_blocks, and the trailing
        scratch block never enters circulation."""
        if not self._debug_pool:
            return
        # the audit READS allocator state wholesale — from a non-owning
        # thread that races the very invariants it checks, but only
        # while a dispatch is actually in flight (tests legitimately
        # audit a quiesced engine from the main thread after stop())
        if self._inflight > 0:
            self._assert_pool_owner("_check_pool_invariants")
        free = set(self._free_blocks)
        cached = set(self._lru)
        quarantined = set(self._quarantine)
        live = [p for blocks in self._slot_blocks for p in blocks]
        live_set = set(live)
        assert len(free) == len(self._free_blocks), "free heap duplicates"
        pools = (free, cached, live_set, quarantined)
        for i, a in enumerate(pools):
            for bset in pools[i + 1:]:
                assert not (a & bset), "block in two pools"
        assert free | cached | live_set | quarantined == \
            set(range(self.n_blocks)), (
            f"pool leak: free({len(free)}) + cached({len(cached)}) + "
            f"live({len(live_set)}) + quarantined({len(quarantined)}) "
            f"!= n_blocks({self.n_blocks})")
        for phys in quarantined:
            assert self._write_fence.get(phys), \
                f"unfenced block {phys} stuck in quarantine"
        for phys in list(cached) + list(free):
            # the fence is authoritative at release: a fenced block must
            # never sit in a pool the allocation ladder hands out from
            # (_pop_block pops the free heap / evicts the LRU with no
            # fence check)
            assert not self._write_fence.get(phys), \
                f"fenced block {phys} in an allocatable pool"
        refs = collections.Counter(live)
        for phys in range(self.n_blocks):
            assert self._block_ref[phys] == refs.get(phys, 0), (
                f"block {phys}: refcount {self._block_ref[phys]} != "
                f"{refs.get(phys, 0)} table references")
        for b in range(self.B):
            blocks = self._slot_blocks[b]
            row = self._tables[b]
            assert list(row[:len(blocks)]) == blocks, f"table row {b} drift"
            assert all(x == -1 for x in row[len(blocks):]), \
                f"table row {b} stale tail"
        for phys in cached:
            assert phys in self._block_hash, \
                f"unregistered block {phys} in the cached LRU"

    def _ensure_blocks(self, slot_idx, upto_pos):
        """Blocks covering positions [0, upto_pos]. False = pool dry."""
        need = upto_pos // self.block_size + 1
        have = len(self._slot_blocks[slot_idx])
        return need <= have or self._alloc_blocks(slot_idx, need - have)

    def prefill_blocks_needed(self, prompt_len):
        """Pool blocks the prefill of a ``prompt_len``-token prompt must
        cover. THE one copy of this arithmetic — admission, the
        too-small-pool check, the self-preempt recoverability guard, and
        the serving layer's synchronous validation all call it. Legacy
        admission writes whole chunk windows (chunk-rounded, block-
        quantized); the fused scheduler drop-scatters exact token
        positions, so it needs the prompt's own blocks plus the one the
        FIRST decode token grows into (position prompt_len) — without
        that +1 a block-aligned prompt that exactly fills the pool would
        admit, ramp fully, then silently retire 'preempted_pool' with
        zero tokens where the legacy path raises the loud too-small-pool
        error."""
        if self.scheduler == "fused":
            return -(-(prompt_len + 1) // self.block_size)
        pad_end = min(-(-prompt_len // self.chunk) * self.chunk,
                      self.capacity)
        return -(-pad_end // self.block_size)

    def _kernel_tp_ctx(self):
        """Trace-time TP routing for the Pallas paged kernels: while
        active, ``block_multihead_attention``'s TPU fast path shard_maps
        the decode/append kernels over the tp axis (each shard reads its
        own kv-head slice of the pools; block tables and seq_lens ride
        in replicated). Only trace time matters — the wrapped dispatches
        are already-compiled calls afterwards — and the context is inert
        without a tp mesh (or on CPU, where the dense fallback under
        GSPMD partitions itself)."""
        import contextlib
        if self._tp_axis is None or self.cache_impl != "paged":
            return contextlib.nullcontext()
        from ..ops.kernels.paged_attention import paged_tp_context
        return paged_tp_context(self._mesh, self._tp_axis)

    def tp_degree(self):
        """Size of the engine's tensor-parallel mesh axis (1 = single
        chip)."""
        return self._tp_size

    # ------------------------------------------------------------------
    # KV-pool capacity accounting (quantized serving)
    # ------------------------------------------------------------------
    def kv_pool_nbytes(self):
        """Total (global) device bytes of the paged K/V pools INCLUDING
        the quantization scale arrays; 0 on dense engines. Summed off
        the real buffers' shapes, so the capacity acceptance (an int8
        pool fits >= 1.9x, int4 >= 3.5x the bf16 block count at equal
        HBM bytes) is asserted against what is actually allocated, not
        a side formula."""
        if self.cache_impl != "paged":
            return 0
        return sum(int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
                   for x in jax.tree_util.tree_leaves([self._k, self._v]))

    def kv_bytes_per_block(self):
        """Device bytes ONE pool block costs across all layers (K + V
        payload plus its per-head scales) — what the serve bench's
        equal-byte pool sizing divides a HBM budget by."""
        if self.cache_impl != "paged":
            return 0
        return self.kv_pool_nbytes() // (self.n_blocks + 1)

    def kv_pool_effective_blocks(self):
        """Pool capacity in BF16-EQUIVALENT blocks: how many unquantized
        blocks the pool's HBM bytes would have held — n_blocks on an
        unquantized pool, ~2x/~4x n_blocks under int8/int4 (minus the
        scale overhead). The ``kv_pool_effective_blocks`` Prometheus
        gauge samples this: capacity dashboards read one number that is
        comparable across pool dtypes."""
        if self.cache_impl != "paged":
            return 0
        if not self.kv_quant:
            return self.n_blocks
        unquant = self._n_layers * 2 * self._kvh * self.block_size * \
            self._head_dim * np.dtype(self._np_dt).itemsize
        return int(self.n_blocks * unquant
                   // max(self.kv_bytes_per_block(), 1))

    def max_pipeline_depth(self):
        """How many step_begin() dispatches may be in flight at once.

        The depth contract (mirrored as a table in
        docs/architecture.md):

        * **fused, dense**: 3 — every grant decision reads the
          scheduler's own lens mirror (``_Slot.sched_len`` counts
          in-flight growth), finish/preemption detection tolerates
          up-to-(depth-1)-steps-stale host state (a slot that finished
          in flight keeps dispatching until its first readout; later
          pendings drop its column via the slot-identity check), and
          the in-graph guards bound over-decode.
        * **fused, paged, full pool** (>= max_batch * blocks-per-slot):
          3 — allocation cannot fail in steady state. One TRANSIENT
          exception: a slot retiring while later dispatches still fence
          its blocks parks them in quarantine for up to depth-1
          step_finishes, so a boundary-crossing slot (or a fresh
          admission) in that window can find the heap short and take
          the ladder's partial-coverage clamp — or, worst case, a
          preemption, which stays token-exact (re-prefill + the
          per-(rid, position) sampling keys) and self-heals as the
          fences drain.
        * **fused, paged, oversubscribed**: 2 — the stride-aware
          in-flight WRITE FENCE makes mid-flight eviction memory-safe
          (a victim's blocks quarantine until the dispatches that may
          still write them land, so they are never handed to a new
          owner early), but every stale step a preemption decision
          lags costs re-prefill churn, so the contract caps the lag at
          one dispatch.
        * **fused speculative** (``speculative_k > 1``): 2 — the
          verify-grant lens mirror overestimates in-flight growth by
          every rejected tail (the device rolls back, the host learns
          at readout), so each extra stale dispatch over-fences and
          over-allocates a full window per slot; the contract caps the
          lag at one dispatch, which the rollback/quarantine machinery
          is proven against.
        * **legacy dense / speculative**: 2 (the original in-graph-
          guard contract — host request state is one step stale at the
          chained dispatch).
        * **legacy paged**: 1 — legacy slots have no in-flight lens
          mirror; the block allocator and the admission prefill train
          need each step's post-readout lens."""
        if self.scheduler == "fused":
            if self.speculative_k > 1:
                return 2
            if self.cache_impl != "paged" or \
                    self.n_blocks >= self.B * self._max_blocks:
                return 3
            return 2
        if self.cache_impl == "paged":
            return 1
        return 2

    def _release_slot_blocks(self, slot_idx):
        """Release every block slot ``slot_idx`` references and wipe its
        table row — shared by retirement (_free_slot) and the pool-dry
        admission rollback. Releases the DEEPEST block first: the LRU
        then evicts leaves before their chain parents (evicting a prefix
        head first would orphan every descendant still cached under
        it)."""
        for phys in reversed(self._slot_blocks[slot_idx]):
            self._release_block(phys)
        self._slot_blocks[slot_idx] = []
        self._tables[slot_idx, :] = -1
        self._check_pool_invariants()

    def _free_slot(self, slot_idx):
        slot = self.slots[slot_idx]
        if self.cache_impl == "paged":
            self._release_slot_blocks(slot_idx)
        if slot is not None:
            # drop this request's pin on its adapter's device slot (a
            # refcount-0 slot parks in the adapter LRU, still loaded —
            # the tenant's next request hits without a swap)
            self._release_adapter(getattr(slot.req, "adapter_id", 0))
        self.slots[slot_idx] = None

    def _preempt_newest(self, exclude=None, newer_than=None, retired=None):
        """Pool pressure: evict the most recently admitted active slot back
        to the FRONT of the waiting queue (its committed tokens join the
        prompt, so re-prefill reproduces the identical greedy state) and
        free its blocks. ``newer_than`` restricts candidates to slots
        admitted AFTER that order stamp — a requester may only evict slots
        newer than itself, or the preempt-newest invariant inverts (a new
        arrival evicting an older, further-along request, then thrashing
        as the roles swap every re-admission). Returns the evicted slot
        index or None."""
        candidates = [b for b, s in enumerate(self.slots)
                      if s is not None and b != exclude
                      and (newer_than is None
                           or self._admit_order[b] > newer_than)]
        if not candidates:
            return None
        b = max(candidates, key=lambda i: self._admit_order[i])
        self._preempt_slot(b, retired=retired)
        return b

    def _retire_pool_edge(self, b, retired=None):
        """Retire slot ``b`` at the pool edge with the distinct
        'preempted_pool' reason (not 'capacity' — that is the engine's
        sequence-length cap). THE one copy of the retire block — the
        recoverability guard, the legacy coverage loop's sole-slot case,
        and the fused scheduler's coverage all call it."""
        slot = self.slots[b]
        out = RequestOutput(
            slot.req.request_id,
            self._finish_tokens(slot.req, slot.generated), True,
            "preempted_pool")
        self.finished_outputs[slot.req.request_id] = out
        if retired is not None:
            retired.append(out)
        self._free_slot(b)
        return out

    def _preempt_slot(self, b, retired=None):
        """Evict slot ``b`` back to the FRONT of the waiting queue: its
        committed tokens join the prompt so re-prefill reproduces the
        identical greedy state, and its blocks free for older slots.

        Recoverability guard: chunk-rounded re-prefill can need MORE
        blocks than the slot currently holds, so a grown prompt may no
        longer fit the pool AT ALL — parking it would stall the FIFO and
        end in the loud too-small-pool error, losing its stream. Such a
        slot retires gracefully instead (finish_reason 'preempted_pool',
        appended to ``retired`` so step_finish returns it)."""
        slot = self.slots[b]
        req = slot.req
        done = np.concatenate([req.prompt_ids,
                               np.asarray(slot.generated, np.int32)])
        if self.prefill_blocks_needed(len(done)) > self.n_blocks:
            self._retire_pool_edge(b, retired)
            return
        if self.kv_host_swap:
            # demote the committed KV to host RAM BEFORE the blocks
            # release — re-admission then restores it (one H2D copy +
            # a one-token stitch) instead of re-prefilling the stream.
            # Unconditional on purpose: with the prefix cache on, the
            # registered full blocks often survive in the LRU/spill
            # store too, but only the swap entry covers the PARTIAL
            # tail block and content eviction races — the gather is one
            # async dispatch whose copy hides under the next step.
            self._swap_out_slot(b, slot)
        prefix = self._preempted_prefix.get(req.request_id, [])
        self._preempted_prefix[req.request_id] = \
            list(prefix) + list(slot.generated)
        self.waiting.appendleft(GenerationRequest(
            req.request_id, done,
            req.max_new_tokens - len(slot.generated),
            req.temperature, req.top_p, req.eos_token_id,
            readout_stride=req.readout_stride,
            adapter_id=req.adapter_id, kind=req.kind,
            spec_ewma=req.spec_ewma, export_kv=req.export_kv))
        self._free_slot(b)
        self.stats["preemptions"] += 1
        if self._rec() is not None:
            self._rec_preempted.append(req.request_id)

    def _finish_tokens(self, req, generated):
        """Full output stream incl. tokens committed before a preemption.
        Called exactly once per TERMINAL output, so it also drops the
        request's persisted acceptance-EWMA entry (kept across
        preemption and restart, dead weight after the finish)."""
        prefix = self._preempted_prefix.pop(req.request_id, [])
        self._spec_ewma.pop(req.request_id, None)
        if self.cache_impl == "paged":
            # a terminal output's host-tier swap entry is dead weight
            # (and a rid-reuse hazard) — drop it with the stitch state
            self._swap_store.pop(req.request_id, None)
        return list(prefix) + list(generated)

    def _admit(self, slot_idx, req, a_slot=0):
        """Chunked prefill of `req` into slot `slot_idx`. Dispatches are
        ASYNC (no host read), so chunk programs pipeline on device; the
        admit_time_s stat records only the host-side enqueue cost — the
        device-side prefill compute lands inside the next decode read.
        Paged mode returns False when the pool can't cover the prompt.
        ``a_slot``: the request's adapter device row (already acquired
        by the caller) — prefill KV must carry the adapter's deltas."""
        t0 = time.perf_counter()
        self._programs()
        P = len(req.prompt_ids)
        paged = self.cache_impl == "paged"
        # single-sequence prefill: the LoRA gather sees a batch of one
        lora1 = self._lora_pack(np.array([a_slot], np.int32))
        hit, chain = 0, []
        if paged:
            if self.prefix_cache:
                # longest cached prefix, CHUNK-granular here: legacy
                # prefill scatters whole chunk windows and must never
                # scatter into a shared block, so the hit boundary must
                # be a window boundary
                hit, chain = self._probe_prefix(slot_idx, req.prompt_ids,
                                                chunk_granular=True,
                                                adapter_id=req.adapter_id)
            # prefill writes whole chunks: cover round_up(P, chunk), then
            # release the over-allocation down to the prompt's own blocks
            # (chunk is a block multiple, so blocks-needed * block_size
            # IS the padded end position)
            pad_end = self.prefill_blocks_needed(P) * self.block_size
            if not self._ensure_blocks(slot_idx, pad_end - 1):
                # pool dry — roll the acquired hit back (the request
                # requeues; its shared refs must not pin cached blocks)
                self._release_slot_blocks(slot_idx)
                return False
        off = hit
        logits_row = None
        # ONE zero-padded prompt buffer per admit, sliced per window (the
        # old loop re-allocated a chunk-sized np.zeros and re-copied the
        # table row for EVERY chunk — pure host overhead on the admission
        # path), and ONE table-row copy: the row doesn't change during the
        # loop (blocks were allocated above).
        padded = np.zeros((max(-(-P // self.chunk) * self.chunk,
                               self.chunk),), np.int32)
        padded[:P] = req.prompt_ids
        table_row = self._tables[slot_idx].copy() if paged else None
        # legacy admission prefills BEFORE the step dispatches: its chunk
        # spans stamp the id the upcoming dispatch will take, so request
        # time still joins back to a StepRecord
        rec = self._rec()
        if hit:
            self.stats["prefix_hit_tokens"] += hit
            if rec is not None:
                rec.req_event(req.request_id, "cached_prefix",
                              step_id=rec.next_step_id(), value=hit)
        while off < P:
            take = min(self.chunk, P - off)
            if paged:
                # chunk windows stay block-aligned (off is a multiple of
                # chunk; capacity % chunk == 0), no slide-back needed
                win = off
            else:
                # JAX dynamic slices CLAMP out-of-range starts, so a window
                # that would cross the buffer end slides BACK instead:
                # positions [win, off) are recomputed (producing identical
                # KV) and the new tokens land exactly at [off, off+take)
                win = min(off, self.capacity - self.chunk)
            chunk_ids = padded[win:win + self.chunk][None]
            if paged:
                self._k, self._v, logits_row = self._prefill_paged_fn(
                    self._state_vals, self._k, self._v, chunk_ids,
                    table_row, np.int32(win),
                    np.int32(off + take - 1 - win), lora=lora1)
            else:
                self._k, self._v, logits_row = self._prefill_fn(
                    self._state_vals, self._k, self._v, chunk_ids,
                    np.int32(slot_idx), np.int32(win),
                    np.int32(off + take - 1 - win), lora=lora1)
            off += take
            self.stats["prefill_chunks"] += 1
            self.stats["prefill_tokens"] += take
            if rec is not None:
                rec.req_event(req.request_id, "prefill",
                              step_id=rec.next_step_id(), value=take)
        if paged:
            # drop the chunk-padding over-allocation: keep only the blocks
            # the prompt actually occupies (+ the one decode grows into).
            # Popped blocks are always the fresh private tail of the
            # allocation (hit blocks sit below keep), so release just
            # returns them to the free heap.
            keep = P // self.block_size + 1
            blocks = self._slot_blocks[slot_idx]
            while len(blocks) > keep:
                phys = blocks.pop()
                self._tables[slot_idx, len(blocks)] = -1
                self._release_block(phys)
        self._admit_order[slot_idx] = self._admit_seq
        self._admit_seq += 1
        self._logits = self._set_logits_fn(self._logits, logits_row,
                                           np.int32(slot_idx))
        self._lens = self._set_len_fn(self._lens, np.int32(slot_idx),
                                      np.int32(P))
        if self._tokens is not None:
            # token history for in-graph drafting: the prompt, zero-padded
            row = np.zeros((self.capacity,), np.int32)
            row[:P] = req.prompt_ids
            self._tokens = self._set_tokens_fn(
                self._tokens, row, np.int32(slot_idx))
        slot = _Slot(req, P)
        slot.chain = chain
        slot.reg_blocks = len(chain)
        slot.a_slot = a_slot
        self.slots[slot_idx] = slot
        if paged:
            # the whole prompt is prefilled: publish its full blocks'
            # content (hit blocks are already registered and skip)
            self._register_upto(slot_idx, slot, P)
            self._check_pool_invariants()
        self.stats["admit_time_s"] += time.perf_counter() - t0

    def _admit_fused(self, slot_idx, req, a_slot=0):
        """Fused-scheduler admission: slot ASSIGNMENT plus (prefix cache
        on) the content-store probe — hit blocks attach by table writes
        and refcount bumps, the optional COW tail costs one block clone,
        and ``prefill_pos`` starts AT the hit boundary so the step
        scheduler grants zero prefill for the shared span. No prefill
        dispatch, no other block allocation (both happen chunk-by-chunk
        inside the step scheduler); admission stays O(hit blocks) and
        never stalls running decodes."""
        t0 = time.perf_counter()
        self._programs()
        hit, chain = 0, []
        # swap-store gate, not kv_host_swap: a SHIPPED entry (import_kv)
        # must suppress the prefix probe the same way a local swap does,
        # even on engines with preempt-swap off
        swapped = self.cache_impl == "paged" and req.kind != "embed" and \
            req.request_id in self._swap_store
        if self.prefix_cache and req.kind != "embed":
            # embed requests never PROBE: a hit would skip the shared
            # span's hidden-state computation and corrupt the mean pool.
            # They still REGISTER their filled blocks (the KV content is
            # a pure function of tenant + tokens), so a later generate
            # request of the same tenant hits them.
            hit, chain = self._probe_prefix(slot_idx, req.prompt_ids,
                                            adapter_id=req.adapter_id,
                                            no_cow=swapped)
        probe_hit = hit
        # a live swap entry restores LAZILY in the scheduler
        # (_try_swap_restores, the next mixed step): the pool is often
        # dry at the exact re-admission moment, and consuming the entry
        # then would forfeit the restore a retirement one step later
        # could have paid for — admission just seeds the stitch at the
        # probe hit
        self._lens = self._set_len_fn(self._lens, np.int32(slot_idx),
                                      np.int32(hit))
        if self._tokens is not None:
            # speculative fused engine: seed the device token history
            # with the WHOLE prompt (host-known even for a prefix-cache
            # hit span) so prompt-lookup drafts can match into it
            row = np.zeros((self.capacity,), np.int32)
            row[:len(req.prompt_ids)] = req.prompt_ids
            self._tokens = self._set_tokens_fn(self._tokens, row,
                                               np.int32(slot_idx))
        if req.kind == "embed":
            # fresh mean-pool accumulator for this slot's new occupant
            self._pooled = self._set_pooled_fn(self._pooled,
                                               np.int32(slot_idx))
        slot = _Slot(req, len(req.prompt_ids), prefill_pos=hit)
        slot.chain = chain
        slot.reg_blocks = len(chain)
        slot.a_slot = a_slot
        self.slots[slot_idx] = slot
        if probe_hit:
            # only the CONTENT-STORE hit counts as a prefix hit — the
            # swap-restored span is booked on the kv_swap_* stats
            self.stats["prefix_hit_tokens"] += probe_hit
            rec = self._rec()
            if rec is not None:
                rec.req_event(req.request_id, "cached_prefix",
                              step_id=rec.next_step_id(), value=probe_hit)
        self._admit_order[slot_idx] = self._admit_seq
        self._admit_seq += 1
        self.stats["admit_time_s"] += time.perf_counter() - t0

    def _admit_waiting(self):
        fused = self.scheduler == "fused"
        for b in range(self.B):
            if not self.waiting:
                break
            if self.slots[b] is None:
                req = self.waiting[0]
                room = self.capacity - len(req.prompt_ids) - \
                    self.speculative_k
                if req.max_new_tokens > room:
                    import warnings
                    warnings.warn(
                        f"request {req.request_id}: capping max_new_tokens "
                        f"{req.max_new_tokens} -> {room} (engine capacity "
                        f"{self.capacity})", RuntimeWarning, stacklevel=3)
                    req.max_new_tokens = room
                if fused and self.cache_impl == "paged":
                    need = self.prefill_blocks_needed(len(req.prompt_ids))
                    if need > self.n_blocks:
                        # can NEVER ramp in: leave it at the head;
                        # step_begin raises the loud too-small-pool error
                        break
                    # admission-defer PROGRESS GUARANTEE (the fused-ramp
                    # livelock fix): a ramping slot must never be
                    # admitted while the pool cannot cover its ramp AND
                    # the outstanding ramp demand of already-resident
                    # ramping slots — otherwise two ramps over a pool
                    # barely larger than one prompt trade blocks through
                    # the preempt ladder forever (preempt newest →
                    # re-admit → re-grab → preempt), burning prefill
                    # FLOPs without either finishing (the 2-slot ×
                    # 4-block-prompt × 4-block-pool thrash PR 12's bench
                    # surfaced). Deferring costs nothing: the resident
                    # ramp can always finish alone, and its retirement
                    # re-opens admission.
                    ramp_deficit = sum(
                        max(self.prefill_blocks_needed(s.prompt_len)
                            - len(self._slot_blocks[i]), 0)
                        for i, s in enumerate(self.slots)
                        if s is not None and s.ramping)
                    if ramp_deficit and \
                            need + ramp_deficit > self._n_allocatable():
                        break
                a_slot = self._acquire_adapter(req)
                if a_slot is None:
                    # every adapter cache slot is pinned by resident
                    # requests: defer (a retirement releases one) —
                    # exactly the dry-pool admission shape
                    break
                self.waiting.popleft()
                if fused:
                    self._admit_fused(b, req, a_slot)
                elif self._admit(b, req, a_slot) is False:
                    # paged pool dry: requeue and wait for a retirement
                    self.waiting.appendleft(req)
                    self._release_adapter(req.adapter_id)
                    break

    # ------------------------------------------------------------------
    # the engine loop
    # ------------------------------------------------------------------
    def step(self):
        """Admit waiting requests into free slots, run ONE decode step for
        all active slots, retire finished requests. Returns the list of
        RequestOutput finished by this step."""
        pending = self.step_begin()
        if pending is None:
            return []
        return self.step_finish(pending)

    def _rec(self):
        """The attached FlightRecorder when it is recording, else None —
        the one-attribute-check gate every hook goes through."""
        r = self.flight_recorder
        return r if (r is not None and r.enabled) else None

    def _record_dispatch(self, pending, kind, grants, scheduled, budget,
                         dispatch_s, readout_stride=1):
        """Emit this dispatch's StepRecord (recorder attached and armed
        by step_begin) and stamp ``pending`` with its step id. The
        admit/schedule splits come from the engine's own stats deltas
        anchored at step_begin entry, so the record can't drift from
        what the engine measured."""
        rec, ctx = self._rec(), self._rec_ctx
        if rec is None or ctx is None:
            return
        t0, admit0, hits0, swaps0, kvin0, kvout0, shin0, shout0 = ctx
        wall = time.perf_counter() - t0
        admit_s = self.stats["admit_time_s"] - admit0
        paged = self.cache_impl == "paged"
        preempted = tuple(self._rec_preempted) + tuple(
            o.request_id for o in pending.pool_done)
        pending.step_id = rec.begin_step(
            scheduler=self.scheduler, kind=kind, grants=grants,
            tokens_scheduled=scheduled, token_budget=budget,
            queue_depth=len(self.waiting),
            free_blocks=len(self._free_blocks) if paged else None,
            total_blocks=self.n_blocks if paged else None,
            pipeline_inflight=self._inflight,
            preemptions=preempted, admit_s=admit_s,
            schedule_s=max(wall - admit_s - dispatch_s, 0.0),
            dispatch_s=dispatch_s, t_begin=t0,
            prefix_hit_tokens=(self.stats["prefix_hit_tokens"] - hits0
                               if self.prefix_cache else None),
            cached_blocks=len(self._lru) if self.prefix_cache else None,
            readout_stride=readout_stride,
            # quantized-KV capacity facts: pool bytes (payload + scales)
            # and the pool storage dtype — what joins a preemption-churn
            # tail back to "the pool was simply small"
            kv_pool_bytes=self._kv_nbytes if paged else None,
            kv_cache_dtype=(self.kv_quant or str(np.dtype(self._np_dt)))
            if paged else None,
            # preemption-SWAP traffic THIS step moved (swap-out at its
            # preemptions, swap-in restores at its scheduling) plus the
            # spill store's point-in-time size — the swap-byte deltas
            # are the exclusive signal splitting the explain_tail
            # preemption cause into swap vs re-prefill (spill/promote
            # traffic books elsewhere on purpose)
            kv_swap_in_bytes=(self.stats["kv_swap_in_bytes"] - kvin0)
            if paged else None,
            kv_swap_out_bytes=(self.stats["kv_swap_out_bytes"] - kvout0)
            if paged else None,
            kv_host_spill_blocks=len(self._spill) if paged else None,
            # cross-replica ship traffic THIS step (a shipped restore's
            # stitch grant rides a mixed step) — the explain_tail
            # "kv_ship" cause's signal, booked apart from swap bytes
            kv_ship_in_bytes=(self.stats["kv_ship_in_bytes"] - shin0)
            if paged else None,
            kv_ship_out_bytes=(self.stats["kv_ship_out_bytes"] - shout0)
            if paged else None,
            # per-slot TENANT ids + this step's adapter swap-ins (the
            # explain_tail "adapter_swap" cause reads them back)
            adapter_slots=tuple(
                (b, s.req.adapter_id) for b, s in enumerate(self.slots)
                if s is not None and s.req.adapter_id),
            adapter_swaps=self.stats["adapter_swaps"] - swaps0)
        self._rec_ctx = None

    # ---- runtime sanitizers (paddle_tpu.analysis) ---------------------
    def _open_stride_guard(self, pending):
        """Arm the one-sync-per-stride contract for an all-decode
        multi-step dispatch: until the guard closes (step_finish, or the
        next chained step_begin under pipelining), ANY implicit device
        transfer on the stepping thread raises — the PR-8 headline claim
        as a runtime assertion instead of a bench number. Explicit
        transfers (jax.device_put / device_get) stay allowed, which is
        exactly the allowlist semantics the documented readout needs."""
        if not self._transfer_checks or \
                getattr(_STRIDE_GUARD_TLS, "cm", None) is not None:
            return
        cm = jax.transfer_guard("disallow")
        cm.__enter__()
        _STRIDE_GUARD_TLS.cm = cm
        _STRIDE_GUARD_TLS.owner = pending
        pending.guarded = True

    @property
    def _stride_guard(self):
        """The CALLING thread's open stride-guard context (None when no
        window is open on this thread) — introspection for tests. The
        slot is shared by all engines on the thread (see
        _STRIDE_GUARD_TLS)."""
        return getattr(_STRIDE_GUARD_TLS, "cm", None)

    def _close_stride_guard(self, finishing=None):
        """Close the CALLING thread's open window, if any (whichever
        engine opened it — one slot per thread; see
        :func:`close_thread_stride_guard`). A jax transfer guard is
        thread-local: another thread's window cannot be closed from
        here — and need not be, since it constrains only that thread;
        it heals when that thread next enters any engine (or is inert
        forever if the thread died with it)."""
        close_thread_stride_guard(finishing)

    def _note_pool_owner(self):
        if self._lock_checks:
            self._pool_owner = threading.get_ident()

    def _assert_pool_owner(self, what):
        """PADDLE_TPU_LOCK_CHECKS=1: the paged-pool allocator, content
        store and quarantine are engine-stepping-thread state (PTL004)
        — there is deliberately no lock on them, so a mutation from any
        other thread is a race. The owner is whichever thread ran the
        last step_begin; reset() clears the pin."""
        if not self._lock_checks or self._pool_owner is None:
            return
        me = threading.get_ident()
        if me != self._pool_owner:
            raise AssertionError(
                f"{what} on thread {me}, but the paged pool is owned by "
                f"engine-stepping thread {self._pool_owner} "
                f"(allocator/quarantine/content-store mutations are "
                f"engine-thread-only; route this through the serve loop "
                f"or take a step-protocol entry point)")

    def step_begin(self):
        """Admit waiting requests into free slots and DISPATCH one decode
        step for all active slots WITHOUT reading anything back. Returns a
        :class:`PendingStep` for :meth:`step_finish`, or None when there is
        nothing to run. Serialized per MODEL object (admission prefill,
        COW clones and the step dispatch all may TRACE through the shared
        model's bind_state — concurrent replica engines on one model must
        not interleave traces).

        Pipelining contract (dense and speculative engines): a second
        ``step_begin()`` may be called before the first ``step_finish()``
        — the chained dispatch consumes the first step's device futures,
        so the device runs ahead of the host by one step. Host request
        state is one step stale at the chained dispatch; that is safe
        because (a) the in-graph guards (eos, budget, capacity) deactivate
        slots from DEVICE state, (b) a slot the host retires between
        dispatch and finish fails the PendingStep identity check and its
        stale tokens are dropped, and (c) over-decode past a budget is
        bounded by one horizon and truncated by the host readout. The
        PAGED engine allocates pool blocks from host lens before each
        dispatch, so it must run depth 1 (finish before the next begin —
        enforced)."""
        # a chained (pipelined) dispatch re-opens host->device traffic:
        # the previous stride's strict window ends here, not at its
        # step_finish
        self._close_stride_guard()
        fi = self.fault_injector
        if fi is not None:
            # the chaos hook fires OUTSIDE the model dispatch lock: an
            # injected hang must wedge only THIS engine's loop, never
            # sibling replicas tracing through the same model object
            fi.on_step_begin(self)
        with self._dispatch_lock:
            return self._step_begin_impl()

    def _step_begin_impl(self):
        from ..core import random as _random

        self._note_pool_owner()
        if self.cache_impl == "paged" and self._spill_inbox:
            # pull-on-miss arrivals land BEFORE admission so a request
            # submitted right after the import probes into them
            self._drain_spill_inbox()
        if self.cache_impl == "paged" and \
                self._inflight >= self.max_pipeline_depth():
            raise RuntimeError(
                "paged engine cannot pipeline step_begin() calls this "
                "deep: its block allocator needs the previous step's "
                "lens (step_finish the outstanding PendingStep first; "
                "see max_pipeline_depth())")
        if self._rec() is not None:
            # wall-split anchors for this step's record: entry time,
            # admit-stat baseline (scheduling = wall - admit - dispatch),
            # prefix-hit + adapter-swap baselines (the record carries
            # this step's deltas)
            self._rec_ctx = (time.perf_counter(),
                             self.stats["admit_time_s"],
                             self.stats["prefix_hit_tokens"],
                             self.stats["adapter_swaps"],
                             self.stats["kv_swap_in_bytes"],
                             self.stats["kv_swap_out_bytes"],
                             self.stats["kv_ship_in_bytes"],
                             self.stats["kv_ship_out_bytes"])
            self._rec_preempted = []
        self._admit_waiting()
        if not any(s is not None for s in self.slots):
            if self.waiting and self.cache_impl == "paged":
                # nothing running AND the head request couldn't admit: the
                # pool simply cannot hold its prompt — fail loudly rather
                # than letting generate() spin forever
                req = self.waiting[0]
                P = len(req.prompt_ids)
                need = self.prefill_blocks_needed(P)
                if need > self.n_blocks:
                    raise PoolCapacityError(
                        f"request {req.request_id}: prefilling its "
                        f"{P}-token prompt needs {need} KV blocks but the "
                        f"pool has {self.n_blocks} total (kv_pool_blocks "
                        f"too small)")
            return None
        self._programs()
        if self._rng_key is None:
            if self._sampling_seed is not None:
                # replica-independent base key (disaggregated serving):
                # every engine built with the same sampling_seed derives
                # identical per-(rid, position) fold_in keys, so a
                # migrated sampled stream continues token-exactly
                key = jax.random.PRNGKey(self._sampling_seed)
            else:
                seed, counter = _random.default_generator.next_seed()
                key = jax.random.fold_in(jax.random.PRNGKey(seed), counter)
            if self._mesh is not None:
                # multi-process: the key must be a GLOBAL replicated array
                # (every process derives the identical value from the seed)
                from jax.sharding import NamedSharding, PartitionSpec
                # ptlint: disable=PTL001 -- one-time rng seed pull at the
                # FIRST step only (self._rng_key is None exactly once per
                # reset), never in the per-stride dispatch->readout window
                data = np.asarray(jax.random.key_data(key))
                glob = jax.make_array_from_callback(
                    data.shape,
                    NamedSharding(self._mesh, PartitionSpec()),
                    lambda idx: data[idx])
                key = jax.random.wrap_key_data(glob)
            self._rng_key = key
        spec = self.speculative_k > 1
        pool_budget, pool_done = {}, []
        if self.scheduler == "fused" and \
                any(s is not None and s.ramping for s in self.slots):
            # at least one slot is ramping in: ONE fused mixed dispatch
            # covers its prefill chunk AND every decode slot's token
            # (or, speculative engine, its verify window). All-decode
            # steps fall through to the plain scan below (horizon
            # amortization intact in steady state).
            return self._begin_mixed_step(pool_done)
        if spec and self.scheduler == "fused":
            # fused SPECULATIVE all-decode: every slot runs verify
            # windows through the multi-window program (readout_stride
            # composes — a stride step is `stride` windows with the
            # same in-graph early exit)
            return self._begin_spec_decode(pool_done)
        # ALL-DECODE fast path: with readout_stride > 1 the fused
        # scheduler runs up to `stride` decode iterations as one
        # multi-step dispatch (in-graph early exit); the token-budget
        # walk degenerates to ONE decode grant of `stride` tokens per
        # slot, and block coverage below is pre-granted for the whole
        # stride. Legacy engines keep stride == horizon (the scan).
        stride = self._effective_stride()
        if self.cache_impl == "paged":
            # block coverage for the stride's growth (last written
            # position is cur + stride - 1); pool pressure first grabs
            # whatever blocks remain free (partial coverage + a budget
            # clamp beats eviction), then evicts the newest slots, and
            # only retires at the pool edge when a slot can't even write
            # one more token
            order = sorted((b for b, s in enumerate(self.slots)
                            if s is not None),
                           key=lambda i: self._admit_order[i])
            for b in order:
                if self.slots[b] is None:
                    continue  # evicted below while ensuring an older slot
                slot = self.slots[b]
                if slot.req.kind == "embed":
                    # fully-ramped embed slot awaiting its pooled
                    # readout: no decode growth, no block coverage
                    continue
                # sched_len counts in-flight growth too: under the fused
                # scheduler's pipelining the host allocates for step N+1
                # before step N's readout (legacy engines run depth 1
                # here, where sched_len == current length)
                cur = slot.sched_len()
                last_pos = min(cur + stride - 1, self.capacity - 1)
                while not self._ensure_blocks(b, last_pos):
                    avail = self._n_allocatable()
                    if avail:
                        self._alloc_blocks(b, avail)
                    covered = len(self._slot_blocks[b]) * self.block_size
                    if covered > cur:
                        pool_budget[b] = covered - cur
                        break
                    victim = self._preempt_newest(
                        exclude=b, newer_than=self._admit_order[b],
                        retired=pool_done)
                    if victim is None:
                        # no NEWER victim: this slot is the newest active.
                        # If OLDER slots are still running, self-preempt —
                        # park the request back on the waiting queue (its
                        # re-prefill path reproduces the identical greedy
                        # state; _preempt_slot's recoverability guard
                        # retires it instead when the grown prompt has
                        # outgrown the pool) and let it resume once an
                        # older slot retires and frees blocks. Only the
                        # SOLE active slot must retire outright (parking
                        # it would readmit into the same dry pool and
                        # spin) — with the distinct 'preempted_pool'
                        # reason, not 'capacity' (the engine's
                        # sequence-length cap).
                        if any(s is not None and i != b
                               for i, s in enumerate(self.slots)):
                            self._preempt_slot(b, retired=pool_done)
                            break
                        self._retire_pool_edge(b, pool_done)
                        break

        # embed slots never DECODE: one fully ramped but unread (its
        # pooled readout rides an earlier in-flight dispatch) sits
        # inactive in an all-decode step
        active = np.array([s is not None and s.req.kind != "embed"
                           for s in self.slots])
        if not active.any():
            if pool_done:
                pending = PendingStep(None, None, None, spec,
                                      list(self.slots), pool_done)
                # no dispatch, but preemptions/retirements happened —
                # record the drain so the causal chain has no hole
                self._record_dispatch(pending, "drain", (), 0,
                                      self.B * self.horizon, 0.0)
                return pending
            return None
        temps, top_ps, eos_ids, rids, budgets = \
            self._slot_sampling_arrays()
        for b, cap_left in pool_budget.items():
            budgets[b] = min(budgets[b], cap_left)

        # the stride-aware in-flight write fence (paged fused): every
        # block this dispatch may write — from each slot's COMMITTED
        # length through its scheduled stride — is fenced until
        # step_finish, so a mid-flight eviction can never hand one to a
        # new owner (see _fence_blocks / _release_block)
        fenced = []
        if self.cache_impl == "paged" and self.scheduler == "fused":
            for b, slot in enumerate(self.slots):
                if slot is None or not active[b]:
                    continue
                lo = slot.prefill_pos + len(slot.generated)
                hi = min(slot.sched_len() + stride - 1, self.capacity - 1)
                self._fence_blocks(b, lo, hi, fenced)

        # multi-step all-decode (readout_stride): one compiled k-step
        # loop with in-graph early exit — the host sync amortizes over
        # up to `stride` tokens per slot. Pinned latency-tier requests
        # (effective stride 1), horizon engines and legacy engines keep
        # the scan path — a readout_stride=1 engine is bit-identical to
        # the pre-stride engine by construction.
        use_multi = self.readout_stride > 1 and stride > 1

        # gathered per-slot adapter rows (None while no adapter is
        # registered — the dispatch then traces the pre-adapter body)
        lora = self._lora_pack(self._slot_adapter_rows())

        # the decode clock starts HERE: pool-allocator scans and host array
        # construction above must not masquerade as device decode time in
        # throughput() or the serve bench's wall split. All arms DISPATCH
        # only — no host read; JAX async dispatch returns futures and the
        # transfer blocks in step_finish().
        t0 = time.perf_counter()
        counts = None
        if use_multi:
            fn = self._multi_fn(stride)
            if self.cache_impl == "paged":
                with self._kernel_tp_ctx():
                    (toks, was_active, self._logits, self._k, self._v,
                     self._lens, self._rng_key) = fn(
                        self._state_vals, self._k, self._v, self._logits,
                        self._lens, active, self._rng_key, temps, top_ps,
                        eos_ids, budgets, rids, self._tables.copy(),
                        lora=lora)
            else:
                (toks, was_active, self._logits, self._k, self._v,
                 self._lens, self._rng_key) = fn(
                    self._state_vals, self._k, self._v, self._logits,
                    self._lens, active, self._rng_key, temps, top_ps,
                    eos_ids, budgets, rids, lora=lora)
            self.stats["multi_steps"] += 1
        elif self.cache_impl == "paged":
            with self._kernel_tp_ctx():
                (toks, was_active, self._logits, self._k, self._v,
                 self._lens, self._rng_key) = self._step_paged_fn(
                    self._state_vals, self._k, self._v, self._logits,
                    self._lens, active, self._rng_key, temps, top_ps,
                    eos_ids, budgets, rids, self._tables.copy(),
                    lora=lora)
        elif spec:
            (toks, counts, was_active, self._logits, self._k, self._v,
             self._lens, self._rng_key, self._tokens) = self._spec_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, active, self._rng_key,
                temps, top_ps, eos_ids, budgets, rids, self._tokens)
        else:
            (toks, was_active, self._logits, self._k, self._v, self._lens,
             self._rng_key) = self._step_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, active, self._rng_key,
                temps, top_ps, eos_ids, budgets, rids, lora=lora)
        dt = time.perf_counter() - t0
        self.stats["dispatch_time_s"] += dt
        self.stats["decode_time_s"] += dt
        self._inflight += 1
        sched = {}
        if self.scheduler == "fused":
            # host lens mirror for the paged pipeline: a surviving slot
            # grows exactly `stride` tokens per dispatch (every in-graph
            # early-deactivation — eos, budget, capacity — also retires
            # the slot at readout, so the mirror never undershoots a
            # live slot; an early EXIT below the stride only ever
            # accompanies such a deactivation)
            for b, slot in enumerate(self.slots):
                if slot is not None and active[b]:
                    slot.inflight += stride
                    sched[b] = stride
        pending = PendingStep(
            toks, was_active, counts, spec, list(self.slots), pool_done,
            sched=sched, fenced=fenced,
            # legacy verify scan: full-width windows per active slot —
            # the shared readout's acceptance accounting reads this
            verify=({int(b): self.speculative_k - 1
                     for b in np.nonzero(active)[0]
                     if self.slots[b] is not None} if spec else None))
        pending.t_dispatch = t0
        if use_multi:
            # all-decode stride dispatched: arm the strict
            # dispatch->readout window (no-op unless
            # PADDLE_TPU_TRANSFER_CHECKS=1)
            self._open_stride_guard(pending)
        if self._rec() is not None:
            # ONE decode grant per slot covering the whole stride (spec:
            # stride verify windows of up to Kspec each)
            per_slot = stride * (self.speculative_k if spec else 1)
            grants = tuple(
                (b, s.req.request_id, "decode", per_slot)
                for b, s in enumerate(self.slots)
                if s is not None and active[b])
            self._record_dispatch(
                pending, "spec" if spec else "decode", grants,
                sum(g[3] for g in grants), self.B * per_slot, dt,
                readout_stride=per_slot)
        return pending

    def _slot_sampling_arrays(self, budgets=True):
        """Per-slot traced sampling inputs of one dispatch — THE one
        copy of the array construction (temps, top_ps, eos_ids, rids,
        and optionally remaining budgets) shared by the all-decode,
        speculative and mixed dispatch builders, so a new per-request
        field can never silently desynchronize one path."""
        temps = np.array([s.req.temperature if s else 0.0
                          for s in self.slots], np.float32)
        top_ps = np.array([s.req.top_p if s else 1.0
                           for s in self.slots], np.float32)
        eos_ids = np.array([(s.req.eos_token_id if s and
                             s.req.eos_token_id is not None else -1)
                            for s in self.slots], np.int32)
        # per-slot request ids ride into the dispatch: sampling keys are
        # fold_in(fold_in(base, rid), position) — see sample_next
        rids = np.array([s.req.request_id if s else 0
                         for s in self.slots], np.int32)
        if not budgets:
            return temps, top_ps, eos_ids, rids
        buds = np.array([(s.req.max_new_tokens - len(s.generated))
                         if s else 0 for s in self.slots], np.int32)
        return temps, top_ps, eos_ids, rids, buds

    # ------------------------------------------------------------------
    # fused scheduler: speculative all-decode dispatch (verify windows)
    # ------------------------------------------------------------------
    def _begin_spec_decode(self, pool_done):
        """ALL-DECODE dispatch of the fused SPECULATIVE engine: every
        active generate slot gets one VERIFY grant — 1 committed token
        plus its acceptance-adaptive draft count per window — run as
        ``stride`` windows in one compiled while_loop with in-graph
        early exit (the multi-step composition), through the append-form
        attention path. Rejected drafts roll back in-graph (lens) and,
        for paged slots, by host block-table truncation at readout.
        Pool pressure SHRINKS windows (per-slot ``row_caps``) before
        anyone is preempted — only a slot that cannot even write its
        committed token walks the preempt ladder."""
        stride = self._effective_stride()
        Kw = self.speculative_k
        paged = self.cache_impl == "paged"
        spec_qs = np.zeros((self.B,), np.int32)
        row_caps = np.full((self.B,), self.capacity, np.int32)
        order = sorted((b for b, s in enumerate(self.slots)
                        if s is not None),
                       key=lambda i: self._admit_order[i])
        for b in order:
            slot = self.slots[b]
            if slot is None or slot.req.kind == "embed":
                continue
            cur = slot.sched_len()
            if cur >= self.capacity:
                continue  # pipelined overshoot; readout retires it
            kd = self._spec_k_for(slot)
            if paged:
                want_hi = min(cur + stride * (1 + kd) - 1,
                              self.capacity - 1)
                if not self._ensure_blocks(b, want_hi):
                    avail = self._n_allocatable()
                    if avail:
                        self._alloc_blocks(b, avail)
                    covered = len(self._slot_blocks[b]) * self.block_size
                    if covered <= cur:
                        # cannot even write the committed token: the
                        # ordinary coverage ladder (preempt newer /
                        # park / retire at the pool edge)
                        if not self._ensure_pos_covered(b, cur,
                                                        pool_done):
                            continue
                        covered = len(self._slot_blocks[b]) * \
                            self.block_size
                    row_caps[b] = min(int(row_caps[b]), covered)
            spec_qs[b] = 1 + kd
        active = np.array([spec_qs[b] > 0 and self.slots[b] is not None
                           for b in range(self.B)])
        if not active.any():
            if pool_done:
                pending = PendingStep(None, None, None, True,
                                      list(self.slots), pool_done)
                self._record_dispatch(pending, "drain", (), 0,
                                      self.B * Kw * stride, 0.0)
                return pending
            return None
        temps, top_ps, eos_ids, rids, budgets = \
            self._slot_sampling_arrays()
        lora = self._lora_pack(self._slot_adapter_rows())
        # stride-aware write fence over every position this dispatch's
        # windows may write (committed length .. the scheduled stride of
        # full windows, clamped by coverage) — _fence_blocks clamps to
        # the blocks the slot actually holds
        fenced = []
        if paged:
            for b in np.nonzero(active)[0]:
                slot = self.slots[b]
                lo = slot.prefill_pos + len(slot.generated)
                hi = min(slot.sched_len() + stride * int(spec_qs[b]) - 1,
                         self.capacity - 1)
                self._fence_blocks(int(b), lo, hi, fenced)

        t0 = time.perf_counter()
        fn = self._multi_spec_fn(stride)
        if paged:
            with self._kernel_tp_ctx():
                (toks, counts, was_active, self._logits, self._k,
                 self._v, self._lens, self._rng_key, self._tokens,
                 offered) = fn(
                    self._state_vals, self._k, self._v, self._logits,
                    self._lens, active, self._rng_key, temps, top_ps,
                    eos_ids, budgets, rids, spec_qs, row_caps,
                    self._tokens, tables=self._tables.copy(), lora=lora)
        else:
            (toks, counts, was_active, self._logits, self._k, self._v,
             self._lens, self._rng_key, self._tokens, offered) = fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, active, self._rng_key, temps, top_ps,
                eos_ids, budgets, rids, spec_qs, row_caps, self._tokens,
                lora=lora)
        dt = time.perf_counter() - t0
        self.stats["dispatch_time_s"] += dt
        self.stats["decode_time_s"] += dt
        self.stats["fused_steps"] += 1
        if stride > 1:
            self.stats["multi_steps"] += 1
        self._inflight += 1
        sched, verify = {}, {}
        for b in np.nonzero(active)[0]:
            slot = self.slots[b]
            if slot is not None:
                # mirror the WORST-CASE growth (full acceptance every
                # window); the readout pays the whole grant back and
                # the committed count lands in slot.generated, so the
                # overestimate lives only while the dispatch is in
                # flight (the depth-2 contract)
                n = stride * int(spec_qs[b])
                slot.inflight += n
                sched[int(b)] = n
                verify[int(b)] = int(spec_qs[b]) - 1
        pending = PendingStep(toks, was_active, counts, True,
                              list(self.slots), pool_done, sched=sched,
                              fenced=fenced, verify=verify)
        pending.t_dispatch = t0
        pending.offered = offered
        if stride > 1:
            # speculative all-decode stride: same one-sync-per-stride
            # window as the dense multi-step path
            self._open_stride_guard(pending)
        if self._rec() is not None:
            grants = tuple(
                (int(b), self.slots[b].req.request_id, "verify",
                 stride * int(spec_qs[b]))
                for b in np.nonzero(active)[0]
                if self.slots[b] is not None)
            self._record_dispatch(
                pending, "spec", grants, sum(g[3] for g in grants),
                self.B * Kw * stride, dt, readout_stride=Kw * stride)
        return pending

    # ------------------------------------------------------------------
    # fused scheduler: the mixed prefill+decode step
    # ------------------------------------------------------------------
    def _ensure_pos_covered(self, b, pos, retired):
        """Cover decode position ``pos`` for slot ``b``, preempting NEWER
        slots under pool pressure (the horizon-1 mirror of the legacy
        coverage loop). Returns False when slot ``b`` itself had to be
        preempted (parked) or retired at the pool edge."""
        while not self._ensure_blocks(b, pos):
            victim = self._preempt_newest(
                exclude=b, newer_than=self._admit_order[b], retired=retired)
            if victim is not None:
                continue
            if any(s is not None and i != b
                   for i, s in enumerate(self.slots)):
                self._preempt_slot(b, retired=retired)
            else:
                # sole active slot at the pool edge: parking it would
                # readmit into the same dry pool and spin
                self._retire_pool_edge(b, retired)
            return False
        return True

    def _schedule_mixed(self, pool_done):
        """One token-budget scheduling pass: per slot, either one decode
        token (always granted — the budget bounds prefill interference,
        not decode progress), a VERIFY grant (speculative engine: the
        committed token is always granted, its acceptance-adaptive
        draft count rides the budget and shrinks first under budget or
        pool pressure), or a prefill chunk grant of up to ``min(chunk,
        remaining prompt, budget left)`` tokens, walked in admission
        order so older requests ramp first. Paged slots allocate their
        blocks HERE (the allocator moved into the unified scheduler); a
        ramping slot that can't cover its grant shrinks it to the
        blocks it could grab and otherwise waits for a retirement."""
        B, S = self.B, self.chunk
        paged = self.cache_impl == "paged"
        spec = self.speculative_k > 1
        ids = np.zeros((B, S), np.int32)
        q_lens = np.zeros((B,), np.int32)
        spec_ks = np.zeros((B,), np.int32) if spec else None
        is_dec = np.zeros((B,), bool)
        active = np.zeros((B,), bool)
        sched = {}
        budget = self.max_step_tokens
        order = sorted((b for b, s in enumerate(self.slots)
                        if s is not None),
                       key=lambda i: self._admit_order[i])
        for b in order:                      # decode slots first
            slot = self.slots[b]
            if slot is None or slot.ramping:
                continue
            if slot.req.kind == "embed":
                # prefill-only: a fully-ramped embed slot gets NO decode
                # grant — it just awaits its pooled readout (the
                # dispatch that carried its final chunk is in flight)
                continue
            cur = slot.sched_len()
            if cur >= self.capacity:
                continue  # pipelined overshoot; readout retires it
            if paged and not self._ensure_pos_covered(b, cur, pool_done):
                continue
            q = 1
            if spec:
                # verify grant: 1 committed token (always) + adaptive
                # drafts, shrunk by the remaining budget and by the
                # blocks the pool could actually cover — drafts are the
                # first thing pool/budget pressure takes away
                kd = min(self._spec_k_for(slot), max(budget - 1, 0))
                if paged and kd > 0 and \
                        not self._ensure_blocks(b, cur + kd):
                    avail = self._n_allocatable()
                    if avail:
                        self._alloc_blocks(b, avail)
                    covered = len(self._slot_blocks[b]) * self.block_size
                    kd = max(0, min(kd, covered - cur - 1))
                spec_ks[b] = kd
                q = 1 + kd
            q_lens[b] = q
            is_dec[b] = True
            active[b] = True
            sched[b] = q
            budget -= q
        first_ramp = True
        for b in order:                      # then prefill grants
            slot = self.slots[b]
            if slot is None or not slot.ramping:
                continue
            # progress guarantee: even when decode tokens alone exhaust
            # the budget (max_step_tokens < live decode slots), the
            # OLDEST ramping slot still gets one token — otherwise a
            # pathological budget starves ramp-in behind long decodes
            grant_cap = budget if budget > 0 else (1 if first_ramp else 0)
            if grant_cap <= 0:
                continue
            pos = slot.prefill_pos
            take = min(S, slot.prompt_len - pos, grant_cap)
            if paged and take > 0 and \
                    not self._ensure_blocks(b, pos + take - 1):
                avail = self._n_allocatable()
                if avail:
                    self._alloc_blocks(b, avail)
                covered = len(self._slot_blocks[b]) * self.block_size
                take = min(take, covered - pos)
            if take <= 0:
                continue
            # the guaranteed token is spent only on a grant that LANDED —
            # a pool-blocked oldest ramp must not eat it while a younger
            # ramping slot with covered blocks could make progress
            first_ramp = False
            ids[b, :take] = slot.req.prompt_ids[pos:pos + take]
            q_lens[b] = take
            active[b] = True
            budget -= take
        return ids, q_lens, is_dec, active, sched, spec_ks

    def _begin_mixed_step(self, pool_done):
        """Schedule and DISPATCH one fused mixed step (>= 1 slot is
        ramping): the whole ramp-in costs one dispatch per engine step
        instead of O(prompt_len / chunk) serial admission dispatches with
        every decode slot stalled behind them."""
        # host-tier swap-ins fire HERE, displacing the prefill grants
        # they make redundant (prefill_pos jumps to the stitch before
        # the budget walk sees the slot)
        self._try_swap_restores()
        for _ in range(self.B + 1):
            ids, q_lens, is_dec, active, sched, spec_ks = \
                self._schedule_mixed(pool_done)
            if active.any():
                break
            # nothing schedulable: every assigned slot is ramping into a
            # dry pool — park the newest (frees blocks for an older ramp;
            # _preempt_slot's recoverability guard retires hopeless ones)
            if self._preempt_newest(retired=pool_done) is None:
                break
        if not active.any():
            if pool_done:
                pending = PendingStep(None, None, None, False,
                                      list(self.slots), pool_done)
                self._record_dispatch(pending, "drain", (), 0,
                                      self.max_step_tokens, 0.0)
                return pending
            return None
        temps, top_ps, _, rids = self._slot_sampling_arrays(budgets=False)
        lora = self._lora_pack(self._slot_adapter_rows())
        # prefill-only plumbing: pass the pooled accumulator (and the
        # embed-slot mask) only while an embed request is RESIDENT, so
        # generate-only serving keeps the untouched no-embed program
        embed_rows = [b for b, s in enumerate(self.slots)
                      if s is not None and s.req.kind == "embed"]
        is_embed = pooled_arg = None
        if embed_rows:
            is_embed = np.zeros((self.B,), bool)
            is_embed[embed_rows] = True
            pooled_arg = self._pooled

        # in-flight write fence over this mixed dispatch's spans: the
        # decode token / verify window per decode slot, the granted
        # chunk span per ramping slot (see _fence_blocks)
        fenced = []
        if self.cache_impl == "paged":
            for b in np.nonzero(active)[0]:
                slot = self.slots[b]
                lo = slot.prefill_pos + len(slot.generated)
                hi = slot.sched_len() + int(q_lens[b]) - 1 if is_dec[b] \
                    else slot.prefill_pos + int(q_lens[b]) - 1
                self._fence_blocks(int(b), lo, min(hi, self.capacity - 1),
                                   fenced)

        spec = self.speculative_k > 1
        spec_args = dict(tokens_buf=self._tokens, spec_ks=spec_ks) \
            if spec else {}
        counts_dev = None
        t0 = time.perf_counter()
        if self.cache_impl == "paged":
            with self._kernel_tp_ctx():
                ret = self._fused_fn(
                    self._state_vals, self._k, self._v, self._logits,
                    self._lens, self._rng_key, ids, q_lens, is_dec,
                    active, temps, top_ps, rids, self._tables.copy(),
                    lora=lora, is_embed=is_embed, pooled=pooled_arg,
                    **spec_args)
        else:
            ret = self._fused_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, self._rng_key, ids, q_lens, is_dec, active,
                temps, top_ps, rids,
                lora=lora, is_embed=is_embed, pooled=pooled_arg,
                **spec_args)
        offered = None
        if spec:
            # spec layout: [1, B, Kw] window tokens + [1, B] counts —
            # the readout flatten shared with the legacy verify scan
            (toks, counts_dev, was_active, self._logits, self._k,
             self._v, self._lens, self._rng_key, pooled_out,
             self._tokens, offered) = ret
        else:
            (toks, was_active, self._logits, self._k, self._v,
             self._lens, self._rng_key, pooled_out) = ret
        if pooled_out is not None:
            self._pooled = pooled_out
        dt = time.perf_counter() - t0
        self.stats["dispatch_time_s"] += dt
        self.stats["decode_time_s"] += dt
        self.stats["fused_steps"] += 1
        # host mirrors of the scheduled growth (dispatch-time, so the
        # next step — possibly dispatched before this one's readout —
        # schedules from the post-step state)
        embed_done = []
        verify = {}
        for b in np.nonzero(active)[0]:
            slot = self.slots[b]
            if is_dec[b]:
                slot.inflight += int(q_lens[b])
                if spec:
                    verify[int(b)] = int(spec_ks[b])
            else:
                slot.prefill_pos += int(q_lens[b])
                self.stats["prefill_chunks"] += 1
                self.stats["prefill_tokens"] += int(q_lens[b])
                if self.prefix_cache:
                    # blocks this grant fills are prompt content — publish
                    # them now so a same-prefix request admitted next step
                    # already hits (device reads happen in later
                    # dispatches, after this grant's write lands)
                    self._register_upto(int(b), slot, slot.prefill_pos)
                if slot.req.kind == "embed" and not slot.ramping:
                    # this dispatch carries the embed request's FINAL
                    # chunk: its pooled row is complete once the step's
                    # device work lands — step_finish reads + retires
                    embed_done.append((int(b), slot))
        self._inflight += 1
        pending = PendingStep(toks, was_active, counts_dev, spec,
                              list(self.slots), pool_done, sched=sched,
                              fenced=fenced, embed_done=embed_done,
                              verify=verify)
        pending.t_dispatch = t0
        pending.pooled = pooled_out
        pending.offered = offered
        rec = self._rec()
        if rec is not None:
            grants = tuple(
                (int(b), self.slots[b].req.request_id,
                 ("verify" if spec else "decode") if is_dec[b]
                 else ("embed" if self.slots[b].req.kind == "embed"
                       else "prefill"), int(q_lens[b]))
                for b in np.nonzero(active)[0] if self.slots[b] is not None)
            self._record_dispatch(pending, "mixed", grants,
                                  sum(g[3] for g in grants),
                                  self.max_step_tokens, dt,
                                  readout_stride=(self.speculative_k
                                                  if spec else 1))
            for _, rid, gkind, n in grants:
                if gkind in ("prefill", "embed"):
                    rec.req_event(rid, "prefill",
                                  step_id=pending.step_id, value=n)
        return pending

    def step_finish(self, pending):
        """Block on ``pending``'s device→host token transfer, attribute the
        tokens to the slots captured at dispatch time, retire finished
        requests. Returns the list of RequestOutput finished by this
        step. Tokens of a slot whose occupant changed since dispatch
        (retired, cancelled, preempted — possibly already reused) are
        dropped: they were decoded for the old occupant's state."""
        # the strict stride window ends HERE: the readout below is the
        # stride's one permitted sync. Close before the chaos hook — an
        # injected crash must not leak a thread-local disallow context.
        self._close_stride_guard(finishing=pending)
        fi = self.fault_injector
        if fi is not None:
            fi.on_step_finish(self)
        spec = pending.spec
        rec = self._rec()
        sid = pending.step_id
        if pending.toks is None:
            if rec is not None and sid is not None:
                rec.finish_step(sid, 0.0, 0.0, tuple(
                    o.request_id for o in pending.pool_done))
            return list(pending.pool_done)
        self._inflight -= 1
        # pay the dispatch's scheduled decode growth back off the
        # host-side lens mirror (fused scheduler; {} otherwise)
        for b, n in pending.sched.items():
            slot = pending.slots[b]
            if slot is not None and self.slots[b] is slot:
                slot.inflight = max(0, slot.inflight - n)
        t0 = time.perf_counter()
        if spec:
            toks3 = np.asarray(pending.toks)          # [Kh, B, Kspec]
            counts_np = np.asarray(pending.counts)    # [Kh, B]
            wa_np = np.asarray(pending.was_active)    # [Kh, B]
            # per-window OFFERED widths (fused paths; None on the
            # legacy scan whose grant is never clamped in-graph)
            offered_np = np.asarray(pending.offered) \
                if pending.offered is not None else None
            Kh, B_, Ks = toks3.shape
            # flatten windows into the [rows, B] stream the readout walks;
            # a window row i is live for slot b iff i < counts (acceptance
            # truncates windows, so the stream has per-window gaps — the
            # readout SKIPS dead rows instead of stopping at them)
            toks_np = toks3.transpose(0, 2, 1).reshape(Kh * Ks, B_)
            act_np = ((np.arange(Ks)[None, :, None] <
                       counts_np[:, None, :]) &
                      wa_np[:, None, :]).reshape(Kh * Ks, B_)
        else:
            toks_np = np.asarray(pending.toks)       # [K, B] — THE transfer
            act_np = np.asarray(pending.was_active)  # [K, B]
        dt = time.perf_counter() - t0
        self.stats["host_sync_time_s"] += dt
        self.stats["decode_time_s"] += dt
        self.stats["steps"] += 1
        if pending.guarded:
            # THE stride's one documented D2H sync just happened — the
            # transfer-guard window it closed proved nothing else
            # synced between dispatch and here
            self.stats["guarded_syncs"] += 1
        # the device work (every KV write included) provably landed —
        # the token sync completed — so this dispatch's write fences
        # drop now, BEFORE the readout walk can retire slots and free
        # (possibly quarantined) blocks
        if pending.fenced:
            self._unfence(pending.fenced)
        if self.cache_impl == "paged" and self._swap_pending:
            # host-tier copies issued in the step_begin/step_finish gap
            # overlapped this step's device work — settle them to numpy
            self._drain_swap_writes()

        # batched-readout stamp amortization: a k-row stride drains k
        # device steps in this ONE sync, but those tokens were produced
        # at k distinct device step boundaries spread over the
        # dispatch→sync window — so each row's emit stamp is backdated
        # by the boundaries still ahead of it, and histograms /
        # explain_tail see honest inter-token gaps instead of k-1 zeros
        # and one stride-wide spike. The window divides over the
        # boundaries the device actually RAN — iterations with any
        # activity (an early-exited stride spent its whole window on
        # the rows that executed), and for the spec engine a verify
        # WINDOW is one boundary: its Ks rows commit together, so they
        # share a stamp rather than being spread across gaps that never
        # existed. emit_backdate_s publishes the per-row backdate to
        # the serving layer's stream callback.
        n_exec = 0
        per_row = 0.0
        if spec:
            # flattened row k belongs to verify window k // Ks; wa_np
            # [Kh, B] (from the readout prep above) says which windows
            # the device actually ran
            row_boundary = np.arange(toks_np.shape[0]) // \
                self.speculative_k
            n_exec = int(wa_np.any(axis=1).sum())
        else:
            row_boundary = np.arange(toks_np.shape[0])
            n_exec = int(act_np.any(axis=1).sum())
        if toks_np.shape[0] > 1 and pending.t_dispatch is not None \
                and n_exec > 1:
            per_row = max(
                time.perf_counter() - pending.t_dispatch, 0.0) / n_exec
        now_pc = time.perf_counter()

        t0 = time.perf_counter()
        done = list(pending.pool_done)
        spec_acc_total = spec_rej_total = 0
        for b, slot in enumerate(pending.slots):
            if slot is None or self.slots[b] is not slot:
                # empty at dispatch, or retired/preempted/cancelled (and
                # possibly reused) since: stale column, skip
                continue
            finish_reason = None
            n_read = 0
            for k in range(toks_np.shape[0]):
                if not act_np[k, b]:
                    if spec:
                        # rejected tail of a verify window: later windows
                        # may still hold live tokens
                        continue
                    # deactivated in-graph before this iteration (eos or
                    # capacity hit at an earlier k): nothing more to read
                    break
                tok = int(toks_np[k, b])
                slot.generated.append(tok)
                n_read += 1
                self.stats["tokens_generated"] += 1
                self.emit_backdate_s = \
                    max(n_exec - 1 - int(row_boundary[k]), 0) * per_row
                if rec is not None and sid is not None:
                    # THE token→step join: this token's timeline span
                    # carries the id of the StepRecord that produced it
                    # (stamped at its amortized device step boundary)
                    rec.on_token(slot.req.request_id, sid,
                                 t=now_pc - self.emit_backdate_s)
                if self.stream_callback is not None:
                    self.stream_callback(slot.req.request_id, tok)
                    if self.slots[b] is not slot:
                        # the callback cancelled this request re-entrantly;
                        # stop reading its window and keep the 'cancelled'
                        # output it recorded
                        break
                if slot.req.eos_token_id is not None and \
                        tok == slot.req.eos_token_id:
                    finish_reason = "eos"
                elif len(slot.generated) >= slot.req.max_new_tokens:
                    finish_reason = "length"
                elif slot.prompt_len + len(slot.generated) >= \
                        self.capacity - self.speculative_k:
                    # margin of K: a verify window writes K positions, and
                    # JAX dynamic updates would clamp past the buffer end
                    finish_reason = "capacity"
                if finish_reason:
                    break
            if spec and n_read > 0:
                # drafts that actually landed in an output (row 0 of each
                # window is the committed sample, not a draft). Window
                # width == speculative_k for the legacy scan AND the
                # fused verify grants, so the flattened-row arithmetic
                # is shared.
                Ks = self.speculative_k
                n_committed = sum(
                    1 for k in range(toks_np.shape[0])
                    if act_np[k, b] and k % Ks == 0)
                accepted = max(n_read - n_committed, 0)
                self.stats["draft_tokens_accepted"] += accepted
                # acceptance accounting: proposed = drafts the device
                # actually OFFERED this slot — per-window offered widths
                # read back from the fused programs (the in-graph
                # row_caps/capacity clamp can shrink a window below its
                # grant, and booking the full grant would bias the
                # EWMA/acceptance rate low exactly under pool pressure);
                # the legacy scan never clamps, so its grant IS exact
                if offered_np is not None:
                    proposed = int(np.maximum(
                        offered_np[:, b] - 1, 0)[wa_np[:, b]].sum())
                else:
                    kd = pending.verify.get(b, Ks - 1) if pending.verify \
                        else Ks - 1
                    proposed = int(wa_np[:, b].sum()) * kd
                self.stats["spec_proposed_tokens"] += proposed
                self.stats["spec_accepted_tokens"] += accepted
                spec_acc_total += accepted
                spec_rej_total += max(proposed - accepted, 0)
                if self.slots[b] is slot:
                    # the re-entrant-cancel guard: a stream callback may
                    # have cancelled this request mid-readout — its
                    # _finish_tokens already dropped the persisted EWMA
                    # entry, and updating it here would resurrect a dead
                    # rid's state (leak + stale seed on rid reuse)
                    self._update_spec_ewma(slot, proposed, accepted)
            if self.slots[b] is not slot:
                continue  # cancelled mid-window; don't record a finish
            if self.prefix_cache and n_read > 0:
                # decode-filled blocks register too (multi-turn reuse: a
                # follow-up prompt carrying this conversation's history
                # hits them) — content is the COMMITTED stream only
                self._register_upto(b, slot,
                                    slot.prefill_pos + len(slot.generated))
            if finish_reason:
                if slot.req.export_kv and self.cache_impl == "paged":
                    # stage the committed KV for cross-replica shipping
                    # WHILE the blocks are still allocated — export_kv()
                    # (router thread) pops the staged entry afterwards
                    self._export_slot_kv(b, slot)
                out = RequestOutput(
                    slot.req.request_id,
                    self._finish_tokens(slot.req, slot.generated), True,
                    finish_reason)
                self.finished_outputs[slot.req.request_id] = out
                done.append(out)
                # slot (and its KV blocks) freed; next step admits into it
                self._free_slot(b)
        # BLOCK-TABLE ROLLBACK (paged verify grants): blocks granted for
        # drafts the device rejected are orphaned — release them with NO
        # copy. Blocks still fenced by a younger in-flight dispatch
        # (depth 2: it may carry an in-flight writer) route through the
        # quarantine machinery instead of the free heap, so they are
        # never handed to a new owner early. The keep line is the slot's
        # sched_len — still counting YOUNGER dispatches' scheduled
        # growth, so nothing any in-flight writer may touch is released.
        if self.cache_impl == "paged" and pending.verify:
            bs = self.block_size
            for b in pending.verify:
                slot = pending.slots[b]
                if slot is None or self.slots[b] is not slot:
                    continue  # retired/preempted; blocks already freed
                keep = slot.sched_len() // bs + 1
                blocks = self._slot_blocks[b]
                while len(blocks) > keep:
                    phys = blocks.pop()
                    self._tables[b, len(blocks)] = -1
                    self._release_block(phys)
            self._check_pool_invariants()
        # prefill-only (embed) completions: this dispatch carried each
        # one's FINAL chunk, so ITS pooled output (pending.pooled — not
        # the engine's newest buffer, which belongs to younger in-flight
        # dispatches the readout must not synchronize on) holds the
        # complete rows. One [H] device read per finishing embed
        # request, divided by the prompt length = the mean pool.
        for b, slot in pending.embed_done:
            if self.slots[b] is not slot:
                continue      # cancelled/preempted since dispatch
            vec = np.asarray(pending.pooled[b], np.float32) \
                / max(slot.prompt_len, 1)
            out = RequestOutput(slot.req.request_id, [], True, "embed",
                                embedding=vec)
            self.finished_outputs[slot.req.request_id] = out
            done.append(out)
            self._free_slot(b)
        self.emit_backdate_s = 0.0
        d_emit = time.perf_counter() - t0
        self.stats["emit_time_s"] += d_emit
        if rec is not None and sid is not None:
            rec.finish_step(sid, dt, d_emit,
                            tuple(out.request_id for out in done),
                            spec_accepted=spec_acc_total,
                            spec_rejected=spec_rej_total)
        return done

    def generate(self, prompts, **sampling):
        """Drain-mode convenience: submit all prompts, run steps until every
        request finishes, return outputs in submission order. Pops its
        outputs from `finished_outputs` — long-running step()-driven servers
        should likewise consume step()'s return list and delete (or pop)
        entries they read, or the dict grows without bound."""
        rids = [self.add_request(p, **sampling) for p in prompts]
        while self.has_unfinished():
            self.step()
        return [self.finished_outputs.pop(r) for r in rids]

    def throughput(self):
        dt = self.stats["decode_time_s"]
        return self.stats["tokens_generated"] / dt if dt > 0 else 0.0

    def reset_stats(self):
        for key in self.stats:
            self.stats[key] = 0.0 if key.endswith("_s") else 0


def _bind(state, values):
    from ..jit.functional_call import bind_state
    return bind_state(state, values)


def _lookup_draft(tokens_buf, lens, k_draft, ngram):
    """In-graph prompt-lookup drafting: for each row, match the committed
    history's final `ngram` tokens against the history itself (most recent
    match wins) and propose the `k_draft` tokens that followed it. Falls
    back to repeating the last token — a bad draft only wastes the verify
    window, never changes output."""
    cap = tokens_buf.shape[1]
    idx = jnp.arange(cap)

    def per_row(buf, L):
        tail_start = jnp.maximum(L - ngram, 0)
        tail = jax.lax.dynamic_slice(buf, (tail_start,), (ngram,))
        eq = jnp.ones((cap,), bool)
        for j in range(ngram):
            # buf[i + j] == tail[j] for every window position i
            eq = eq & (jnp.roll(buf, -j) == tail[j])
        m = eq & (idx < (L - ngram))  # exclude the tail's own position
        has = jnp.any(m)
        i_star = cap - 1 - jnp.argmax(jnp.flip(m))  # most recent match
        start = jnp.where(has, i_star + ngram, 0)
        cont = jax.lax.dynamic_slice(buf, (start,), (k_draft,))
        last = buf[jnp.maximum(L - 1, 0)]
        pos = start + jnp.arange(k_draft)
        return jnp.where(has & (pos < L), cont, last).astype(jnp.int32)

    return jax.vmap(per_row)(tokens_buf, lens.astype(jnp.int32))


def _write_window(tokens_buf, window, lens):
    """Append a verify window's tokens to each row's history at its own
    length (rejected-tail positions are overwritten by later windows)."""
    def per_row(buf, w, L):
        return jax.lax.dynamic_update_slice(buf, w, (L,))

    return jax.vmap(per_row)(tokens_buf, window.astype(jnp.int32),
                             lens.astype(jnp.int32))


# NOTE: the old module-level `_spec_accept` (rejection sampling against
# the processed distribution, with residual masking carried across
# windows) was REPLACED by the in-_programs `verify_window` coupled
# rule: a draft is accepted iff it equals the token the engine would
# sample at that position under its per-(rid, position) fold_in key.
# Acceptance probability for a delta proposal is identical (p(draft)),
# but the committed stream is now TOKEN-IDENTICAL to the non-spec
# engine's in sampled mode too — no residual state to lose across a
# window boundary, a preemption, or a supervised restart — and the
# top-k/top-p "nucleus may shift by one token" approximation is gone.
