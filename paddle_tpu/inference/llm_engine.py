"""LLM serving engine — continuous batching over compiled decode steps.

Reference analog: the serving path the reference builds from
AnalysisPredictor (paddle/fluid/inference/api/analysis_predictor.h:101) plus
the fused decode kernels
(python/paddle/incubate/nn/functional/block_multihead_attention.py:1,
masked_multihead_attention.py:1) that PaddleNLP's serving stack drives with
dynamic request batching.

TPU-native design — everything is STATIC shapes so two compiled programs
serve the whole engine lifetime:

  * ``max_batch`` fixed slots; each slot owns a [capacity, H, D] region of
    the per-layer KV buffers and a traced length (``SlotKVCache``), so
    ragged sequences share one compiled decode step.
  * one **decode step** program: sample (per-slot temperature/top-p vectors,
    greedy-vs-sample selected per slot in-graph) -> one-token model step
    writing KV at each slot's own position -> next logits. Varying sampling
    params or slot occupancy never recompiles.
  * one **chunked-prefill** program per chunk size: admits a request by
    streaming its prompt through fixed-size chunks into its slot's KV region
    (dynamic_slice/update on the slot axis), returning last-position logits.
    Chunk padding is masked by causality and overwritten by later writes.
  * requests join and leave BETWEEN steps (continuous batching): a finished
    slot is freed at the step boundary and the next queued request admits
    into it while other slots keep decoding.

Logits stay on device between steps; the only per-step host transfer is the
[B] sampled-token vector that streaming callers need anyway.
"""
from __future__ import annotations

import collections
import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, functional_mode
from ..models.llama import SlotKVCache, _sample_logits_device

__all__ = ["LLMEngine", "GenerationRequest", "RequestOutput"]


@dataclasses.dataclass
class GenerationRequest:
    request_id: int
    prompt_ids: np.ndarray           # [P] int32
    max_new_tokens: int = 64
    temperature: float = 0.0         # <=0 -> greedy
    top_p: float = 1.0
    eos_token_id: int | None = None


@dataclasses.dataclass
class RequestOutput:
    request_id: int
    token_ids: list
    finished: bool = False
    finish_reason: str | None = None


class _Slot:
    __slots__ = ("req", "generated", "prompt_len")

    def __init__(self, req, prompt_len):
        self.req = req
        self.generated = []
        self.prompt_len = prompt_len


class LLMEngine:
    """Continuous-batching engine over a LlamaForCausalLM (works with
    bf16/fp32 and WeightOnlyLinear-quantized weights; under a mesh the
    programs partition by GSPMD like ``generate()``)."""

    def __init__(self, model, max_batch=4, max_seq_len=None, chunk_size=64,
                 top_k=0, stream_callback=None, horizon=1, speculative_k=1,
                 lookup_ngram=3):
        from ..jit.functional_call import collect_state, read_values

        self.model = model
        c = model.config
        self.B = int(max_batch)
        # decode horizon: tokens decoded per step() call as one compiled
        # lax.scan — amortizes the per-step host sync K-fold at the cost of
        # admitting/retiring requests only every K tokens
        self.horizon = max(1, int(horizon))
        # speculative verify window (prompt-lookup drafting, NO reference
        # analog — the snapshot has no speculative decoding): each step
        # commits 1 sampled token plus up to speculative_k-1 host-drafted
        # tokens verified by ONE K-token model call. Exact for greedy slots;
        # sampling slots fall back to 1 token/step in-graph.
        self.speculative_k = max(1, int(speculative_k))
        self.lookup_ngram = max(1, int(lookup_ngram))
        if self.speculative_k > 1 and self.horizon > 1:
            raise ValueError("speculative_k and horizon are mutually "
                             "exclusive decode modes")
        self.capacity = int(max_seq_len or c.max_position_embeddings)
        if self.capacity > c.max_position_embeddings:
            raise ValueError(
                f"max_seq_len {self.capacity} exceeds rope table "
                f"({c.max_position_embeddings})")
        self.chunk = int(chunk_size)
        self.top_k = int(top_k)
        self.stream_callback = stream_callback

        model.eval()
        _, params, _, buffers = collect_state(model)
        self._state = params + buffers
        self._state_vals = read_values(self._state)

        head_dim = c.hidden_size // c.num_attention_heads
        kvh = c.num_key_value_heads
        dt = model.llama.embed_tokens.weight.dtype
        L = c.num_hidden_layers
        # a prefill window is always a full `chunk` wide, so it must fit the
        # buffer (the final window slides BACK over already-written
        # positions instead of padding the time axis — see _admit)
        self.chunk = min(self.chunk, self.capacity)
        shape = (self.B, self.capacity, kvh, head_dim)
        self._k = [jnp.zeros(shape, dt) for _ in range(L)]
        self._v = [jnp.zeros(shape, dt) for _ in range(L)]
        self._logits = jnp.zeros((self.B, c.vocab_size), jnp.float32)
        self._lens = jnp.zeros((self.B,), jnp.int32)
        self._n_layers = L

        # host-side slot table / queues
        self.slots: list[_Slot | None] = [None] * self.B
        self.waiting: collections.deque[GenerationRequest] = \
            collections.deque()
        self.finished_outputs: dict[int, RequestOutput] = {}
        self._next_id = 0
        self._rng_key = None
        self._step_fn = None
        self._prefill_fn = None
        self._set_logits_fn = None
        self.stats = {"steps": 0, "prefill_chunks": 0, "tokens_generated": 0,
                      "draft_tokens_accepted": 0, "decode_time_s": 0.0}

    # ------------------------------------------------------------------
    # compiled programs
    # ------------------------------------------------------------------
    def _programs(self):
        if self._step_fn is not None:
            return
        model = self.model
        state = self._state
        B, cap, chunk = self.B, self.capacity, self.chunk
        top_k = self.top_k

        K = self.horizon

        def one_step(k_bufs, v_bufs, logits, lens, active, rng, state_vals,
                     temps, top_ps, eos_ids):
            """sample from current logits -> one-token model step."""
            rng, sub = jax.random.split(rng)
            greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = _sample_logits_device(
                logits, sub, jnp.maximum(temps, 1e-6)[:, None], top_k,
                top_ps[:, None], False, True)
            nxt = jnp.where(temps <= 0.0, greedy_tok, sampled)
            # inactive slots decode garbage; pin them to token 0
            nxt = jnp.where(active, nxt, 0)
            with functional_mode(), _bind(state, state_vals):
                caches = [SlotKVCache(k, v, lens)
                          for k, v in zip(k_bufs, v_bufs)]
                hidden, new_caches = model.llama(
                    Tensor(nxt[:, None]), kv_caches=caches,
                    position_offset=Tensor(lens))
                new_logits = model._logits(hidden)._value[:, 0] \
                    .astype(jnp.float32)
            kb = [cc.k._value if isinstance(cc.k, Tensor) else cc.k
                  for cc in new_caches]
            vb = [cc.v._value if isinstance(cc.v, Tensor) else cc.v
                  for cc in new_caches]
            new_lens = jnp.where(active, lens + 1, lens)
            finished = active & (nxt == eos_ids)
            return nxt, new_logits, kb, vb, new_lens, finished, rng

        def step(state_vals, k_bufs, v_bufs, logits, lens, active, rng,
                 temps, top_ps, eos_ids, budgets):
            """`horizon` decode iterations as ONE compiled lax.scan — the
            host sync (and through a tunnel, the RTT) amortizes over K
            tokens per slot. A slot that hits eos, capacity, or its
            remaining budget mid-horizon deactivates in-graph; the host
            reads the per-iteration (tokens, active) history to attribute
            outputs."""
            def body(carry, _):
                kb, vb, logits, lens, act, emitted, rng = carry
                nxt, logits, kb, vb, lens, finished, rng = one_step(
                    kb, vb, logits, lens, act, rng, state_vals, temps,
                    top_ps, eos_ids)
                emitted = emitted + act.astype(jnp.int32)
                act_next = act & ~finished & (lens < cap - 1) & \
                    (emitted < budgets)
                return (kb, vb, logits, lens, act_next, emitted, rng), \
                    (nxt, act)

            emitted0 = jnp.zeros_like(lens)
            (k_bufs, v_bufs, logits, lens, active, _, rng), \
                (toks, was_active) = jax.lax.scan(
                    body,
                    (k_bufs, v_bufs, logits, lens, active, emitted0, rng),
                    None, length=K)
            return toks, was_active, logits, k_bufs, v_bufs, lens, rng

        Kspec = self.speculative_k

        def spec_step(state_vals, k_bufs, v_bufs, logits, lens, active, rng,
                      temps, top_ps, eos_ids, draft):
            """Speculative verify window: commit one sampled token, then
            check `draft` [B, Kspec-1] against the model's own greedy
            predictions from ONE Kspec-token call. Acceptance is exact: a
            draft position survives only if every earlier one did and the
            model's prediction matches, so greedy output is identical to
            step-by-step decode whatever the draft quality. KV written past
            the accepted prefix is stale but unreferenced (lens-based masks)
            and is overwritten by the next window, which starts at the new
            length."""
            rng, sub = jax.random.split(rng)
            greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            sampled = _sample_logits_device(
                logits, sub, jnp.maximum(temps, 1e-6)[:, None], top_k,
                top_ps[:, None], False, True)
            committed = jnp.where(temps <= 0.0, greedy_tok, sampled)
            committed = jnp.where(active, committed, 0)
            window = jnp.concatenate([committed[:, None], draft], axis=1)
            with functional_mode(), _bind(state, state_vals):
                caches = [SlotKVCache(k, v, lens)
                          for k, v in zip(k_bufs, v_bufs)]
                hidden, new_caches = model.llama(
                    Tensor(window), kv_caches=caches,
                    position_offset=Tensor(lens))
                logits_all = model._logits(hidden)._value \
                    .astype(jnp.float32)                    # [B, K, V]
            kb = [cc.k._value if isinstance(cc.k, Tensor) else cc.k
                  for cc in new_caches]
            vb = [cc.v._value if isinstance(cc.v, Tensor) else cc.v
                  for cc in new_caches]
            # prediction at window row i is the model's token for position
            # i+1; draft[:, i] survives iff it matches and all before it did
            greedy_next = jnp.argmax(logits_all[:, :-1], axis=-1) \
                .astype(jnp.int32)                          # [B, K-1]
            match = (greedy_next == draft) & active[:, None] & \
                (temps <= 0.0)[:, None]
            acc = jnp.cumprod(match.astype(jnp.int32), axis=1)
            n_acc = acc.sum(axis=1).astype(jnp.int32)       # [B]
            new_logits = jnp.take_along_axis(
                logits_all, n_acc[:, None, None], axis=1)[:, 0]
            new_lens = lens + jnp.where(active, 1 + n_acc, 0)
            return window, n_acc, new_logits, kb, vb, new_lens, rng

        def prefill_chunk(state_vals, k_bufs, v_bufs, ids, slot, off, last):
            """Run chunk `ids` [1, chunk] of one prompt through the model
            against slot `slot`'s KV region starting at position `off`;
            returns updated buffers + the logits at in-chunk row `last`."""
            from ..models.llama import StaticKVCache

            z = jnp.int32(0)
            k_slot = [jax.lax.dynamic_slice(
                k, (slot, z, z, z), (1,) + k.shape[1:]) for k in k_bufs]
            v_slot = [jax.lax.dynamic_slice(
                v, (slot, z, z, z), (1,) + v.shape[1:]) for v in v_bufs]
            with functional_mode(), _bind(state, state_vals):
                caches = [StaticKVCache(k, v)
                          for k, v in zip(k_slot, v_slot)]
                hidden, new_caches = model.llama(
                    Tensor(ids), kv_caches=caches,
                    position_offset=Tensor(off))
                row = jax.lax.dynamic_slice(
                    hidden._value, (z, last, z), (1, 1, hidden.shape[-1]))
                logits_row = model._logits(Tensor(row))._value[0, 0] \
                    .astype(jnp.float32)
            k_out = [jax.lax.dynamic_update_slice(
                kb, (cc.k._value if isinstance(cc.k, Tensor) else cc.k
                     ).astype(kb.dtype), (slot, z, z, z))
                for kb, cc in zip(k_bufs, new_caches)]
            v_out = [jax.lax.dynamic_update_slice(
                vb, (cc.v._value if isinstance(cc.v, Tensor) else cc.v
                     ).astype(vb.dtype), (slot, z, z, z))
                for vb, cc in zip(v_bufs, new_caches)]
            return k_out, v_out, logits_row

        def set_logits(logits, row, slot):
            return jax.lax.dynamic_update_slice(
                logits, row[None].astype(logits.dtype), (slot, jnp.int32(0)))

        self._step_fn = jax.jit(step, donate_argnums=(1, 2, 3))
        self._spec_fn = jax.jit(spec_step, donate_argnums=(1, 2, 3))
        self._prefill_fn = jax.jit(prefill_chunk, donate_argnums=(1, 2))
        self._set_logits_fn = jax.jit(set_logits, donate_argnums=(0,))

    # ------------------------------------------------------------------
    # request lifecycle
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens=64, temperature=0.0,
                    top_p=1.0, eos_token_id=None, request_id=None):
        ids = np.asarray(
            prompt_ids.numpy() if hasattr(prompt_ids, "numpy")
            else prompt_ids, dtype=np.int32).reshape(-1)
        if len(ids) == 0:
            raise ValueError("empty prompt")
        if len(ids) >= self.capacity - self.speculative_k:
            raise ValueError(f"prompt of {len(ids)} tokens leaves no room "
                             f"to generate (engine capacity "
                             f"{self.capacity})")
        rid = self._next_id if request_id is None else request_id
        self._next_id = max(self._next_id, rid) + 1
        self.waiting.append(GenerationRequest(
            rid, ids, int(max_new_tokens), float(temperature), float(top_p),
            eos_token_id))
        return rid

    def has_unfinished(self):
        return bool(self.waiting) or any(s is not None for s in self.slots)

    def cancel(self, request_id):
        """Cancel a waiting or running request. Returns the partial
        RequestOutput (finish_reason 'cancelled'), or None if the id is
        unknown/already finished. A cancelled running slot frees at the
        next step boundary (its KV region is simply reused)."""
        for i, req in enumerate(self.waiting):
            if req.request_id == request_id:
                del self.waiting[i]
                out = RequestOutput(request_id, [], True, "cancelled")
                self.finished_outputs[request_id] = out
                return out
        for b, slot in enumerate(self.slots):
            if slot is not None and slot.req.request_id == request_id:
                out = RequestOutput(request_id, list(slot.generated), True,
                                    "cancelled")
                self.finished_outputs[request_id] = out
                self.slots[b] = None
                return out
        return None

    def _admit(self, slot_idx, req):
        """Chunked prefill of `req` into slot `slot_idx`."""
        self._programs()
        P = len(req.prompt_ids)
        off = 0
        logits_row = None
        while off < P:
            take = min(self.chunk, P - off)
            # JAX dynamic slices CLAMP out-of-range starts, so a window that
            # would cross the buffer end slides BACK instead: positions
            # [win, off) are recomputed (producing identical KV) and the new
            # tokens land exactly at [off, off+take)
            win = min(off, self.capacity - self.chunk)
            chunk_ids = np.zeros((1, self.chunk), np.int32)
            real = req.prompt_ids[win:min(win + self.chunk, P)]
            chunk_ids[0, :len(real)] = real
            self._k, self._v, logits_row = self._prefill_fn(
                self._state_vals, self._k, self._v, jnp.asarray(chunk_ids),
                jnp.int32(slot_idx), jnp.int32(win),
                jnp.int32(off + take - 1 - win))
            off += take
            self.stats["prefill_chunks"] += 1
        self._logits = self._set_logits_fn(self._logits, logits_row,
                                           jnp.int32(slot_idx))
        self._lens = self._lens.at[slot_idx].set(P)
        self.slots[slot_idx] = _Slot(req, P)

    def _admit_waiting(self):
        for b in range(self.B):
            if not self.waiting:
                break
            if self.slots[b] is None:
                req = self.waiting[0]
                room = self.capacity - len(req.prompt_ids) - \
                    self.speculative_k
                if req.max_new_tokens > room:
                    import warnings
                    warnings.warn(
                        f"request {req.request_id}: capping max_new_tokens "
                        f"{req.max_new_tokens} -> {room} (engine capacity "
                        f"{self.capacity})", RuntimeWarning, stacklevel=3)
                    req.max_new_tokens = room
                self.waiting.popleft()
                self._admit(b, req)

    # ------------------------------------------------------------------
    # the engine loop
    # ------------------------------------------------------------------
    def step(self):
        """Admit waiting requests into free slots, run ONE decode step for
        all active slots, retire finished requests. Returns the list of
        RequestOutput finished by this step."""
        from ..core import random as _random

        self._admit_waiting()
        if not any(s is not None for s in self.slots):
            return []
        self._programs()
        if self._rng_key is None:
            seed, counter = _random.default_generator.next_seed()
            self._rng_key = jax.random.fold_in(jax.random.PRNGKey(seed),
                                               counter)
        active = np.array([s is not None for s in self.slots])
        temps = np.array([s.req.temperature if s else 0.0
                          for s in self.slots], np.float32)
        top_ps = np.array([s.req.top_p if s else 1.0
                           for s in self.slots], np.float32)
        eos_ids = np.array([(s.req.eos_token_id if s and
                             s.req.eos_token_id is not None else -1)
                            for s in self.slots], np.int32)
        budgets = np.array([(s.req.max_new_tokens - len(s.generated))
                            if s else 0 for s in self.slots], np.int32)

        t0 = time.perf_counter()
        if self.speculative_k > 1:
            drafts = np.zeros((self.B, self.speculative_k - 1), np.int32)
            for b, slot in enumerate(self.slots):
                # sampling slots reject all drafts in-graph — don't pay the
                # O(context) host lookup for them
                if slot is not None and slot.req.temperature <= 0.0:
                    drafts[b] = self._propose(slot)
            (window, n_acc, self._logits, self._k, self._v, self._lens,
             self._rng_key) = self._spec_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, jnp.asarray(active), self._rng_key,
                jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(eos_ids), jnp.asarray(drafts))
            win_np = np.asarray(window)   # [B, K]
            acc_np = np.asarray(n_acc)    # [B]
            toks_np = win_np.T            # -> [K, B] like the horizon path
            counts = np.where(active, 1 + acc_np, 0)
            act_np = np.arange(toks_np.shape[0])[:, None] < counts[None, :]
        else:
            (toks, was_active, self._logits, self._k, self._v, self._lens,
             self._rng_key) = self._step_fn(
                self._state_vals, self._k, self._v, self._logits,
                self._lens, jnp.asarray(active), self._rng_key,
                jnp.asarray(temps), jnp.asarray(top_ps),
                jnp.asarray(eos_ids), jnp.asarray(budgets))
            toks_np = np.asarray(toks)       # [K, B] — the per-step transfer
            act_np = np.asarray(was_active)  # [K, B]
        self.stats["decode_time_s"] += time.perf_counter() - t0
        self.stats["steps"] += 1

        done = []
        for b, slot in enumerate(self.slots):
            if slot is None:
                continue
            finish_reason = None
            n_read = 0
            for k in range(toks_np.shape[0]):
                if not act_np[k, b]:
                    # deactivated in-graph before this iteration (eos or
                    # capacity hit at an earlier k): nothing more to read
                    break
                tok = int(toks_np[k, b])
                slot.generated.append(tok)
                n_read += 1
                self.stats["tokens_generated"] += 1
                if self.stream_callback is not None:
                    self.stream_callback(slot.req.request_id, tok)
                    if self.slots[b] is not slot:
                        # the callback cancelled this request re-entrantly;
                        # stop reading its window and keep the 'cancelled'
                        # output it recorded
                        break
                if slot.req.eos_token_id is not None and \
                        tok == slot.req.eos_token_id:
                    finish_reason = "eos"
                elif len(slot.generated) >= slot.req.max_new_tokens:
                    finish_reason = "length"
                elif slot.prompt_len + len(slot.generated) >= \
                        self.capacity - self.speculative_k:
                    # margin of K: a verify window writes K positions, and
                    # JAX dynamic updates would clamp past the buffer end
                    finish_reason = "capacity"
                if finish_reason:
                    break
            if self.speculative_k > 1 and n_read > 1:
                # drafts that actually landed in an output (the first token
                # of a window is the committed sample, not a draft)
                self.stats["draft_tokens_accepted"] += n_read - 1
            if self.slots[b] is not slot:
                continue  # cancelled mid-window; don't record a finish
            if finish_reason:
                out = RequestOutput(slot.req.request_id,
                                    list(slot.generated), True,
                                    finish_reason)
                self.finished_outputs[slot.req.request_id] = out
                done.append(out)
                self.slots[b] = None  # slot freed; next step admits into it
        return done

    def _propose(self, slot):
        """Prompt-lookup draft: continue the most recent earlier occurrence
        of the context's final n-gram. The first looked-up token corresponds
        to the in-graph committed token, so the verify window gets the
        remaining speculative_k-1."""
        k = self.speculative_k
        ctx = np.concatenate([slot.req.prompt_ids,
                              np.asarray(slot.generated, np.int32)])
        guess = _prompt_lookup(ctx, k, self.lookup_ngram)
        return guess[1:]

    def generate(self, prompts, **sampling):
        """Drain-mode convenience: submit all prompts, run steps until every
        request finishes, return outputs in submission order. Pops its
        outputs from `finished_outputs` — long-running step()-driven servers
        should likewise consume step()'s return list and delete (or pop)
        entries they read, or the dict grows without bound."""
        rids = [self.add_request(p, **sampling) for p in prompts]
        while self.has_unfinished():
            self.step()
        return [self.finished_outputs.pop(r) for r in rids]

    def throughput(self):
        dt = self.stats["decode_time_s"]
        return self.stats["tokens_generated"] / dt if dt > 0 else 0.0

    def reset_stats(self):
        for key in self.stats:
            self.stats[key] = 0.0 if key.endswith("_s") else 0


def _bind(state, values):
    from ..jit.functional_call import bind_state
    return bind_state(state, values)


def _prompt_lookup(ctx, k, max_ngram=3):
    """Propose k continuation tokens by matching the context's final n-gram
    against its own history (longest n first, most recent match wins).
    Falls back to repeating the last token — a bad draft only wastes the
    verify window, never changes output."""
    ctx = np.asarray(ctx, dtype=np.int32)
    L = len(ctx)
    for n in range(min(max_ngram, L - 1), 0, -1):
        tail = ctx[L - n:]
        for i in range(L - n - 1, -1, -1):
            if np.array_equal(ctx[i:i + n], tail):
                cont = ctx[i + n:i + n + k]
                if len(cont):
                    return np.pad(cont, (0, k - len(cont)),
                                  constant_values=int(ctx[-1]))
        # only fall to shorter n-grams when the longer one has no match
    return np.full(k, int(ctx[-1]), np.int32)
