"""paddle.quantization analog — QAT / PTQ framework.

Reference: python/paddle/quantization/ (QuantConfig in config.py, QAT in
qat.py, PTQ in ptq.py, observers in observer/, quanters in quanter/ — SURVEY.md
§2.6). TPU-native notes: fake-quant runs as a jax custom_vjp (straight-through
estimator) so it fuses into the compiled step; "convert" produces layers whose
weights are stored int8 + scale, computing int8→bf16 dequant inline (XLA fuses
the dequant into the matmul's operand load, the TPU analog of the reference's
quantized kernels).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..nn.layer_base import Layer

__all__ = [
    "QuantConfig", "QAT", "PTQ", "quanters", "observers",
    "AbsmaxObserver", "EMAObserver", "AVGObserver", "MSEObserver",
    "HistObserver", "PerChannelAbsmaxObserver",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMaxObserver",
    "quantize_linear", "dequantize_linear", "fake_quantize",
]


# ---------------------------------------------------------------------------
# fake-quant primitive with STE gradient
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _fake_quant(x, scale, qmin, qmax):
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s), qmin, qmax)
    return q * s


def _fake_quant_fwd(x, scale, qmin, qmax):
    s = jnp.maximum(scale, 1e-9)
    out = jnp.clip(jnp.round(x / s), qmin, qmax) * s
    mask = (x / s >= qmin) & (x / s <= qmax)
    return out, mask


def _fake_quant_bwd(res, g):
    mask = res
    # straight-through: pass gradients inside the clip range, zero outside
    return (g * mask.astype(g.dtype), None, None, None)


_fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)


def fake_quantize(x, scale, bit_length=8, name=None):
    """Simulated quantization with STE backward (reference:
    quanter/base_fake_quanter.py -> fake_quantize_dequantize kernels)."""
    qmax = float(2 ** (bit_length - 1) - 1)

    def fn(v, s):
        return _fake_quant(v, s.astype(v.dtype), -qmax, qmax)

    return dispatch(fn, (x, scale), {}, name="fake_quantize")


def quantize_linear(x, scale, zero_point=None, bit_length=8, axis=None,
                    name=None):
    """Real quantization to int8 (reference: tensor quantize_linear op)."""
    qmax = 2 ** (bit_length - 1) - 1

    def fn(v, s):
        if axis is not None:
            shape = [1] * v.ndim
            shape[axis] = -1
            s = s.reshape(shape)
        return jnp.clip(jnp.round(v / jnp.maximum(s, 1e-9)), -qmax, qmax) \
            .astype(jnp.int8)

    return dispatch(fn, (x, scale), {}, name="quantize_linear")


def dequantize_linear(x, scale, zero_point=None, axis=None, out_dtype="float32",
                      name=None):
    def fn(v, s):
        if axis is not None:
            shape = [1] * v.ndim
            shape[axis] = -1
            s = s.reshape(shape)
        return v.astype(s.dtype) * s

    return dispatch(fn, (x, scale), {}, name="dequantize_linear")


# ---------------------------------------------------------------------------
# observers (reference: quantization/observer/*)
# ---------------------------------------------------------------------------

class BaseObserver(Layer):
    """Collects activation/weight statistics and yields a quant scale."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.quant_bits = quant_bits
        self._scale = None

    def scale(self):
        if self._scale is None:
            raise RuntimeError(f"{type(self).__name__} observed no data yet")
        return self._scale

    def bit_length(self):
        return self.quant_bits

    def quant_axis(self):
        return None

    def observe(self, x):
        raise NotImplementedError

    def forward(self, x):
        self.observe(x)
        return x

    def _qmax(self):
        return float(2 ** (self.quant_bits - 1) - 1)


class AbsmaxObserver(BaseObserver):
    def observe(self, x):
        m = float(np.abs(np.asarray(x._value if isinstance(x, Tensor)
                                    else x)).max())
        self._scale = max(m, self._scale or 0.0) / 1.0
        self._scale = max(self._scale, 1e-9)

    def scale(self):
        super().scale()
        return self._scale / self._qmax()


class EMAObserver(BaseObserver):
    """Moving-average absmax (reference: FakeQuanterWithAbsMaxObserver's EMA)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate

    def observe(self, x):
        m = float(np.abs(np.asarray(x._value if isinstance(x, Tensor)
                                    else x)).max())
        if self._scale is None:
            self._scale = m
        else:
            self._scale = self.moving_rate * self._scale \
                + (1 - self.moving_rate) * m
        self._scale = max(self._scale, 1e-9)

    def scale(self):
        super().scale()
        return self._scale / self._qmax()


class AVGObserver(BaseObserver):
    def __init__(self, quant_bits=8):
        super().__init__(quant_bits)
        self._sum, self._n = 0.0, 0

    def observe(self, x):
        m = float(np.abs(np.asarray(x._value if isinstance(x, Tensor)
                                    else x)).max())
        self._sum += m
        self._n += 1
        self._scale = self._sum / self._n

    def scale(self):
        super().scale()
        return max(self._scale, 1e-9) / self._qmax()


class MSEObserver(BaseObserver):
    """Picks the clip that minimizes quantization MSE over observed batches."""

    def __init__(self, quant_bits=8, candidates=20):
        super().__init__(quant_bits)
        self.candidates = candidates
        self._samples = []
        self._n_stored = 0
        self._dirty = True

    _MAX_STORED = 1 << 20

    def observe(self, x):
        # cheap per-batch: subsample and stash; the clip search runs lazily in
        # scale(), so calibration is O(n_batches), not O(n^2)
        v = np.asarray(x._value if isinstance(x, Tensor) else x).ravel()
        if v.size > 65536:
            v = v[:: v.size // 65536]
        self._samples.append(v.astype(np.float32))
        self._n_stored += v.size
        if self._n_stored > self._MAX_STORED:
            data = np.concatenate(self._samples)
            data = data[:: max(data.size // (self._MAX_STORED // 2), 1)]
            self._samples = [data]
            self._n_stored = data.size
        self._dirty = True
        self._scale = self._scale or 1.0  # mark "has data"

    def _search(self):
        data = np.concatenate(self._samples)
        absmax = float(np.abs(data).max())
        qmax = self._qmax()
        best, best_err = absmax, np.inf
        for frac in np.linspace(0.3, 1.0, self.candidates):
            clip = max(absmax * frac, 1e-9)
            s = clip / qmax
            q = np.clip(np.round(data / s), -qmax, qmax) * s
            err = float(((data - q) ** 2).mean())
            if err < best_err:
                best, best_err = clip, err
        self._scale = max(best, 1e-9)
        self._dirty = False

    def scale(self):
        if not self._samples:
            super().scale()  # raises "observed no data yet"
        if self._dirty:
            self._search()
        return self._scale / self._qmax()


class HistObserver(BaseObserver):
    """Histogram percentile clipping (reference: observer/hist.py)."""

    def __init__(self, quant_bits=8, bins=2048, percent=0.999):
        super().__init__(quant_bits)
        self.bins = bins
        self.percent = percent
        self._hist = None
        self._range = None

    def observe(self, x):
        v = np.abs(np.asarray(x._value if isinstance(x, Tensor) else x)).ravel()
        m = float(v.max()) if v.size else 0.0
        if self._hist is None:
            self._range = max(m, 1e-9)
            self._hist = np.histogram(v, bins=self.bins,
                                      range=(0, self._range))[0].astype(float)
        else:
            if m > self._range:  # stretch: rebin old histogram
                ratio = m / self._range
                idx = (np.arange(self.bins) / ratio).astype(int)
                new_hist = np.zeros(self.bins)
                np.add.at(new_hist, idx, self._hist)
                self._hist = new_hist
                self._range = m
            self._hist += np.histogram(v, bins=self.bins,
                                       range=(0, self._range))[0]
        cdf = np.cumsum(self._hist) / max(self._hist.sum(), 1)
        cut = int(np.searchsorted(cdf, self.percent))
        self._scale = max((cut + 1) / self.bins * self._range, 1e-9)

    def scale(self):
        super().scale()
        return self._scale / self._qmax()


class PerChannelAbsmaxObserver(BaseObserver):
    def __init__(self, quant_bits=8, quant_axis=-1):
        super().__init__(quant_bits)
        self._axis = quant_axis

    def quant_axis(self):
        return self._axis

    def observe(self, x):
        v = np.abs(np.asarray(x._value if isinstance(x, Tensor) else x))
        axes = tuple(i for i in range(v.ndim) if i != self._axis % v.ndim)
        m = v.max(axis=axes)
        self._scale = m if self._scale is None else np.maximum(self._scale, m)
        self._scale = np.maximum(self._scale, 1e-9)

    def scale(self):
        super().scale()
        return self._scale / self._qmax()


# ---------------------------------------------------------------------------
# quanters — trainable fake-quant wrappers used during QAT
# ---------------------------------------------------------------------------

class FakeQuanterWithAbsMaxObserver(Layer):
    """Activation quanter: EMA absmax scale + STE fake-quant each forward."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self._observer = EMAObserver(bit_length, moving_rate)
        self.bit_length = bit_length

    def forward(self, x):
        # eval before any training step still needs a scale: bootstrap the
        # observer from the first tensor it sees (reference initializes the
        # scale buffer similarly)
        if self.training or self._observer._scale is None:
            self._observer.observe(x)
        from ..ops.creation import to_tensor
        return fake_quantize(x, to_tensor(np.float32(self._observer.scale())),
                             self.bit_length)

    def scale(self):
        return self._observer.scale()

    def bit_len(self):
        return self.bit_length


class FakeQuanterChannelWiseAbsMaxObserver(Layer):
    """Weight quanter: per-output-channel absmax (recomputed each forward,
    since weights change under training)."""

    def __init__(self, bit_length=8, quant_axis=-1, dtype="float32", name=None):
        super().__init__()
        self.bit_length = bit_length
        self._axis = quant_axis
        self._observer = PerChannelAbsmaxObserver(bit_length, quant_axis)

    def forward(self, w):
        qmax = float(2 ** (self.bit_length - 1) - 1)
        axis = self._axis

        def fn(v):
            ax = tuple(i for i in range(v.ndim) if i != axis % v.ndim)
            s = jnp.maximum(jnp.max(jnp.abs(v), axis=ax, keepdims=True),
                            1e-9) / qmax
            return _fake_quant(v, s, -qmax, qmax)

        self._observer.observe(w)
        return dispatch(fn, (w,), {}, name="fake_channel_quant")

    def scale(self):
        return self._observer.scale()


class quanters:
    FakeQuanterWithAbsMaxObserver = FakeQuanterWithAbsMaxObserver
    FakeQuanterChannelWiseAbsMaxObserver = FakeQuanterChannelWiseAbsMaxObserver


class observers:
    AbsmaxObserver = AbsmaxObserver
    EMAObserver = EMAObserver
    AVGObserver = AVGObserver
    MSEObserver = MSEObserver
    HistObserver = HistObserver
    PerChannelAbsmaxObserver = PerChannelAbsmaxObserver


# ---------------------------------------------------------------------------
# QuantConfig (reference: quantization/config.py)
# ---------------------------------------------------------------------------

class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_activation = activation
        self._global_weight = weight
        self._layer_cfg = {}   # layer instance id -> (act, w)
        self._type_cfg = {}    # layer class -> (act, w)
        self._name_cfg = {}    # sublayer name -> (act, w)

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def add_name_config(self, names, activation=None, weight=None):
        names = names if isinstance(names, (list, tuple)) else [names]
        for n in names:
            self._name_cfg[n] = (activation, weight)

    def _config_for(self, name, layer):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        if name in self._name_cfg:
            return self._name_cfg[name]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        return (self._global_activation, self._global_weight)


def _make(factory):
    if factory is None:
        return None
    if isinstance(factory, type):
        return factory()
    if callable(factory) and not isinstance(factory, Layer):
        return factory()
    return factory


# ---------------------------------------------------------------------------
# quantized layer wrappers + converted (deploy) layers
# ---------------------------------------------------------------------------

class QuantedLinear(Layer):
    """QAT wrapper (reference: nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = linear
        self.activation_quanter = _make(act_quanter)
        self.weight_quanter = _make(weight_quanter) \
            or FakeQuanterChannelWiseAbsMaxObserver()

    def forward(self, x):
        from ..nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight_quanter(self._inner.weight)
        return F.linear(x, w, self._inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv, act_quanter=None, weight_quanter=None):
        super().__init__()
        self._inner = conv
        self.activation_quanter = _make(act_quanter)
        self.weight_quanter = _make(weight_quanter) \
            or FakeQuanterChannelWiseAbsMaxObserver(quant_axis=0)

    def forward(self, x):
        from ..nn import functional as F
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight_quanter(self._inner.weight)
        c = self._inner
        return F.conv2d(x, w, c.bias, c._stride, c._padding, c._dilation,
                        c._groups, c._data_format)


class QuantizedLinearInfer(Layer):
    """Deploy form: int8 weights + per-channel scales, dequant fused into the
    matmul operand (the XLA analog of a quantized inference kernel).

    With an activation scale (from PTQ calibration) the input is quantized
    too — W8A8: int8×int8 matmul accumulated in int32, rescaled once."""

    def __init__(self, linear, weight_scale, act_scale=None):
        super().__init__()
        w = linear.weight
        scale_np = np.asarray(weight_scale, dtype=np.float32)
        self.w_int8 = quantize_linear(w, Tensor(scale_np), axis=-1)
        self.scales = Tensor(scale_np)
        self.act_scale = None if act_scale is None \
            else float(np.asarray(act_scale))
        self.bias = linear.bias

    def forward(self, x):
        from ..nn import functional as F
        if self.act_scale is not None:
            a_s = self.act_scale

            def fn(xv, q, s, b):
                xq = jnp.clip(jnp.round(xv / a_s), -127, 127)
                y = jnp.matmul(xq.astype(jnp.int32),
                               q.astype(jnp.int32)).astype(s.dtype)
                y = y * (a_s * s)[None, :]
                if b is not None:
                    y = y + b
                return y

            return dispatch(fn, (x, self.w_int8, self.scales, self.bias), {},
                            name="quantized_linear_w8a8")
        w = dequantize_linear(self.w_int8, self.scales, axis=-1)
        return F.linear(x, w, self.bias)


class QuantizedConv2DInfer(Layer):
    """Deploy conv: int8 weights (per-out-channel scale, axis 0), inline
    dequant fused into the conv operand load. Only the int8 weight + bias are
    retained — the fp32 weight is dropped."""

    def __init__(self, conv, weight_scale):
        super().__init__()
        scale_np = np.asarray(weight_scale, dtype=np.float32)
        self.w_int8 = quantize_linear(conv.weight, Tensor(scale_np), axis=0)
        self.scales = Tensor(scale_np)
        self.bias = conv.bias
        self._cfg = (conv._stride, conv._padding, conv._dilation,
                     conv._groups, conv._data_format)

    def forward(self, x):
        from ..nn import functional as F
        w = dequantize_linear(self.w_int8, self.scales, axis=0)
        stride, padding, dilation, groups, fmt = self._cfg
        return F.conv2d(x, w, self.bias, stride, padding, dilation, groups,
                        fmt)


class _ObserverWrapper(Layer):
    """PTQ stage: observe activations, pass through unchanged."""

    def __init__(self, inner, act_observer):
        super().__init__()
        self._inner = inner
        self.act_observer = _make(act_observer)

    def forward(self, x):
        if self.act_observer is not None:
            self.act_observer.observe(x)
        return self._inner(x)


# ---------------------------------------------------------------------------
# QAT / PTQ drivers
# ---------------------------------------------------------------------------

def _swap_sublayers(model, swap_fn):
    """Walk the layer tree, replacing sublayers where swap_fn returns non-None."""
    for name, child in list(model._sub_layers.items()):
        replaced = swap_fn(name, child)
        if replaced is not None:
            model._sub_layers[name] = replaced
        else:
            _swap_sublayers(child, swap_fn)
    return model


class QAT:
    """Quantization-aware training driver (reference: quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def swap(name, layer):
            act, w = self.config._config_for(name, layer)
            if isinstance(layer, Linear):
                return QuantedLinear(layer, act, w)
            if isinstance(layer, Conv2D):
                return QuantedConv2D(layer, act, w)
            return None

        return _swap_sublayers(model, swap)

    def convert(self, model, inplace=False):
        """QAT model -> deploy model with int8 weights."""
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def swap(name, layer):
            if isinstance(layer, QuantedLinear):
                return QuantizedLinearInfer(layer._inner,
                                            layer.weight_quanter.scale())
            if isinstance(layer, QuantedConv2D):
                return QuantizedConv2DInfer(layer._inner,
                                            layer.weight_quanter.scale())
            return None

        return _swap_sublayers(model, swap)


class PTQ:
    """Post-training quantization driver (reference: quantization/ptq.py).

    Usage: q = PTQ(config); model = q.quantize(model); run calibration
    batches; model = q.convert(model)."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model, inplace=False):
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def swap(name, layer):
            if isinstance(layer, (Linear, Conv2D)):
                act, _ = self.config._config_for(name, layer)
                return _ObserverWrapper(layer, act or AbsmaxObserver)
            return None

        return _swap_sublayers(model, swap)

    def convert(self, model, inplace=False):
        from ..nn.layer.common import Linear
        if not inplace:
            import copy
            model = copy.deepcopy(model)

        def swap(name, layer):
            if isinstance(layer, _ObserverWrapper) \
                    and isinstance(layer._inner, Linear):
                w = layer._inner.weight.numpy()
                scales = np.maximum(np.abs(w).max(axis=0), 1e-9) / 127.0
                # calibration result -> W8A8; without it, weight-only
                act_scale = None
                if layer.act_observer is not None \
                        and layer.act_observer._scale is not None:
                    act_scale = layer.act_observer.scale()
                return QuantizedLinearInfer(layer._inner, scales,
                                            act_scale=act_scale)
            if isinstance(layer, _ObserverWrapper):
                return layer._inner
            return None

        return _swap_sublayers(model, swap)


class BaseQuanter(Layer):
    """Abstract quanter contract (reference: quantization/base_quanter.py):
    forward simulates quantization; scales/zero_points/bit_length describe
    the produced quantization parameters."""

    def forward(self, input):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        raise NotImplementedError

    def quant_axis(self):
        return -1

    def bit_length(self):
        return 8


class QuanterFactory:
    """Deferred quanter constructor (reference: quantization/factory.py
    QuanterFactory — holds args, instantiates per layer)."""

    def __init__(self, cls, *args, **kwargs):
        self.partial_class = cls
        self.args = args
        self.kwargs = kwargs

    def _instance(self, layer=None):
        return self.partial_class(*self.args, **self.kwargs)

    def __call__(self, *args, **kwargs):
        return QuanterFactory(self.partial_class, *args, **kwargs)


def quanter(class_name):
    """Register a quanter class under a factory name (reference:
    quantization/factory.py quanter decorator): the decorated class gains a
    same-named factory in this module, so configs can reference it lazily."""
    def wrapper(cls):
        factory = QuanterFactory(cls)
        globals()[class_name] = factory
        import sys
        setattr(sys.modules[__name__], class_name, factory)
        return cls
    return wrapper


__all__ += ["BaseQuanter", "quanter", "QuanterFactory"]
