"""Trace-time LoRA delta context — the MODEL-side half of batched
multi-LoRA serving (the serving-side store/cache/policy live in
:mod:`paddle_tpu.serving.adapters`; this module sits below the model so
``models/llama.py`` can consult it without importing the serving
package).

The engine arms :func:`lora_scope` around its traced model calls with a
pack of TRACED arrays — stacked per-target low-rank factors plus the
per-batch-row device slot vector — and each llama projection asks
:func:`active_lora` whether to add the gathered per-slot delta
``(x @ A[s, l]) @ B[s, l] * alpha[s]`` to its base output. With no scope
armed (the pack is None / the engine has no adapters) the model body
traces completely untouched, so base serving stays bit-identical to the
pre-adapter engine.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["LORA_TARGETS", "lora_target_dims", "lora_scope", "active_lora"]

#: the llama projections an adapter may target, with their (sub-layer,
#: attr) path inside a LlamaDecoderLayer — THE one copy of the table;
#: the store's shape validation, the device stacks, the model-side
#: delta application and apply_merged all consume it.
LORA_TARGETS = (
    ("q_proj", "self_attn"), ("k_proj", "self_attn"),
    ("v_proj", "self_attn"), ("o_proj", "self_attn"),
    ("gate_proj", "mlp"), ("up_proj", "mlp"), ("down_proj", "mlp"),
)


def lora_target_dims(config):
    """target -> (d_in, d_out) for a LlamaConfig."""
    hd = config.hidden_size // config.num_attention_heads
    d = config.hidden_size
    dq = config.num_attention_heads * hd
    dkv = config.num_key_value_heads * hd
    ff = config.intermediate_size
    return {"q_proj": (d, dq), "k_proj": (d, dkv), "v_proj": (d, dkv),
            "o_proj": (dq, d), "gate_proj": (d, ff), "up_proj": (d, ff),
            "down_proj": (ff, d)}


class _LoraState(threading.local):
    ctx = None


_STATE = _LoraState()


class _LoraApply:
    """The armed context: the traced stacks + per-batch-row device slots
    of ONE dispatch, applying the gathered delta on demand."""

    __slots__ = ("A", "B", "alpha", "slots")

    def __init__(self, pack):
        self.A = pack["A"]
        self.B = pack["B"]
        self.alpha = pack["alpha"]
        self.slots = pack["slots"]

    def apply(self, target, layer_idx, x, base):
        """``base + (x @ A[s, l]) @ B[s, l] * alpha[s]`` with ``s`` the
        per-row device slot — fp32 accumulation, cast back to the base
        dtype. ``x``/``base`` are framework Tensors [B, S, d_in/d_out];
        slot 0 gathers the all-zeros base row (delta exactly 0)."""
        import jax.numpy as jnp
        from ..core.tensor import dispatch

        A, Bm = self.A.get(target), self.B.get(target)
        if A is None or Bm is None:
            return base
        alpha, slots = self.alpha, self.slots
        li = int(layer_idx)

        def f(xv, bv):
            Ag = A[slots, li]                   # [B, d_in, r]
            Bg = Bm[slots, li]                  # [B, r, d_out]
            al = alpha[slots]                   # [B]
            h = jnp.einsum("bsd,bdr->bsr", xv.astype(jnp.float32), Ag)
            d = jnp.einsum("bsr,bro->bso", h, Bg) * al[:, None, None]
            return bv + d.astype(bv.dtype)

        return dispatch(f, (x, base), {}, name=f"lora_{target}")


@contextlib.contextmanager
def lora_scope(pack):
    """Arm the LoRA delta for every llama projection dispatched inside —
    the engine wraps its traced model calls in this. ``pack`` is
    ``{"A": {target: [S, L, d_in, r]}, "B": {...}, "alpha": [S],
    "slots": [B]}`` of TRACED arrays (device slots per batch row; 0 =
    base). ``pack=None`` is inert: the model body traces untouched."""
    if pack is None:
        yield
        return
    prev = _STATE.ctx
    _STATE.ctx = _LoraApply(pack)
    try:
        yield
    finally:
        _STATE.ctx = prev


def active_lora():
    """The armed :class:`_LoraApply`, or None — the model-side hook
    (one attribute read on the untraced path)."""
    return _STATE.ctx
