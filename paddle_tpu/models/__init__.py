from .llama import (  # noqa: F401
    LlamaConfig, LlamaModel, LlamaForCausalLM, LlamaDecoderLayer, LlamaAttention,
    LlamaMLP, precompute_rope, apply_rope,
)
from .bert import BertConfig, BertModel, BertForMaskedLM  # noqa: F401
from .gpt import (  # noqa: F401
    GPTConfig, GPT2Model, GPT2LMHeadModel, gpt2_small, gpt2_medium,
)
from .unet import (  # noqa: F401
    UNetConfig, UNetModel, sd_unet, diffusion_loss, timestep_embedding,
)
