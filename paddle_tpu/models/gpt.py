"""GPT-2 family — decoder-only LM over the framework's transformer layers.

Reference analog: the reference keeps GPT in PaddleNLP but exercises it
in-tree through the auto-parallel/dygraph-to-static test models
(e.g. /root/reference/test/auto_parallel/gpt_with_pir.py:1 and
test/legacy_test/test_multi_dot_op.py-style tiny LMs); architecture follows
the public GPT-2: learned positions, pre-LN blocks, tied lm head.

TPU notes: the block stack is the same `nn.TransformerEncoderLayer`
(normalize_before=True) the bert path lowers to flash attention; training
runs under `TrainStep` like every other model; `generate()` decodes through
the layer library's incremental KV caches (`TransformerEncoder.gen_cache`).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..core.tensor import Tensor
from .. import ops
from ..nn import (Dropout, Embedding, Layer, LayerNorm, TransformerEncoder,
                  TransformerEncoderLayer)
from ..nn import functional as F

__all__ = ["GPTConfig", "GPT2Model", "GPT2LMHeadModel", "gpt2_small",
           "gpt2_medium"]


class GPTConfig:
    def __init__(self, vocab_size=50257, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=None,
                 max_position_embeddings=1024, dropout=0.1,
                 layer_norm_eps=1e-5, tie_word_embeddings=True,
                 fuse_lm_head_ce=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.dropout = dropout
        self.layer_norm_eps = layer_norm_eps
        self.tie_word_embeddings = tie_word_embeddings
        # chunked fused (lm_head matmul + CE): never materializes the full
        # [tokens, vocab] logits — the largest single activation of the LM
        # step (see ops/kernels/fused_ce.py fused_linear_ce).
        # CONTRACT: with labels, forward returns (loss, logits) on the
        # unfused path but (loss, <FusedLogitsUnavailable>) under this
        # flag — the placeholder is falsy and raises a RuntimeError naming
        # the flag if consumed (models/common.py). Callers needing logits
        # must run unfused or call without labels.
        self.fuse_lm_head_ce = fuse_lm_head_ce


def gpt2_small(**over):
    return GPTConfig(**{**dict(hidden_size=768, num_hidden_layers=12,
                               num_attention_heads=12), **over})


def gpt2_medium(**over):
    return GPTConfig(**{**dict(hidden_size=1024, num_hidden_layers=24,
                               num_attention_heads=16), **over})


def _causal_mask(s, key_len=None):
    """[1,1,s,key_len] additive causal mask. With a grown KV cache the
    query rows sit at absolute positions [key_len-s, key_len) over keys
    [0, key_len), so row q may see keys k <= (key_len-s)+q."""
    key_len = s if key_len is None else key_len
    offset = key_len - s
    m = jnp.where(jnp.arange(key_len)[None, :] <= offset + jnp.arange(s)[:, None],
                  jnp.float32(0), jnp.float32(-1e30))
    return Tensor(m[None, None])


class GPT2Model(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        c = config
        self.config = c
        self.wte = Embedding(c.vocab_size, c.hidden_size)
        self.wpe = Embedding(c.max_position_embeddings, c.hidden_size)
        self.drop = Dropout(c.dropout)
        block = TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.dropout, activation="gelu", attn_dropout=c.dropout,
            act_dropout=0.0, normalize_before=True,
            layer_norm_eps=c.layer_norm_eps)
        self.h = TransformerEncoder(
            block, c.num_hidden_layers,
            norm=LayerNorm(c.hidden_size, c.layer_norm_eps))

    def forward(self, input_ids, cache=None, position_offset=0):
        s = input_ids.shape[1]
        pos = ops.arange(position_offset, position_offset + s, dtype="int64")
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        if cache is not None:
            # multi-token continuation over a grown cache masks against the
            # absolute key length (cache_len + s); a single decode token
            # attends the whole grown cache freely
            mask = _causal_mask(s, position_offset + s) if s > 1 else None
            return self.h(x, mask, cache)
        return self.h(x, _causal_mask(s))


class GPT2LMHeadModel(Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        from ..nn import Linear

        self.config = config
        self.transformer = GPT2Model(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            return ops.matmul(hidden, self.transformer.wte.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None):
        hidden = self.transformer(input_ids)
        if labels is not None and self.config.fuse_lm_head_ce:
            # chunked fused head over the SHIFTED rows: loss without the
            # full logits tensor; weight is the (tied or untied) output
            # matrix in [hidden, vocab] orientation
            from ..ops.kernels.fused_ce import fused_linear_ce
            from ..core.tensor import dispatch

            tied = self.config.tie_word_embeddings
            w = self.transformer.wte.weight if tied else self.lm_head.weight

            def fn(h2, wv, lbl):
                import jax.numpy as jnp
                wmat = wv.T if tied else wv
                flat = fused_linear_ce(h2, wmat, None, lbl, -100)
                n_valid = jnp.maximum(jnp.sum(lbl != -100), 1)
                return jnp.sum(flat) / n_valid.astype(jnp.float32)

            loss = dispatch(
                fn,
                (ops.reshape(hidden[:, :-1],
                             [-1, self.config.hidden_size]),
                 w, ops.reshape(labels[:, 1:], [-1])), {},
                name="fused_linear_ce_gpt")
            from .common import FusedLogitsUnavailable
            return loss, FusedLogitsUnavailable("fuse_lm_head_ce")
        logits = self._logits(hidden)
        if labels is None:
            return logits
        # causal LM shift: predict token t+1 at position t
        loss = F.cross_entropy(
            ops.reshape(logits[:, :-1], [-1, self.config.vocab_size]),
            ops.reshape(labels[:, 1:], [-1]), ignore_index=-100)
        return loss, logits

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, eos_token_id=None):
        """Incremental decoding through the layer library's KV caches
        (eager path; the flagship compiled serving path is
        paddle_tpu.inference.LLMEngine on the llama family)."""
        from ..core import random as _random

        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(np.asarray(input_ids), jnp.int32))
        B, prompt_len = ids.shape[0], ids.shape[1]
        limit = min(int(max_new_tokens),
                    self.config.max_position_embeddings - prompt_len)
        was_training = self.training
        self.eval()
        try:
            cache = self.transformer.h.gen_cache(
                self.transformer.wte(ids[:, :1]))
            hidden, cache = self.transformer(ids, cache=cache)
            out = []
            finished = np.zeros((B,), bool)
            for i in range(limit):
                logits = self._logits(hidden[:, -1]).numpy()
                if temperature and float(temperature) > 0:
                    logits = logits / float(temperature)
                    if top_k:
                        kth = np.sort(logits, axis=-1)[:, -int(top_k)][:, None]
                        logits = np.where(logits < kth, -np.inf, logits)
                    z = logits - logits.max(-1, keepdims=True)
                    p = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
                    g = _random.default_generator.next_seed()
                    rng = np.random.default_rng(abs(hash(g)) % (2 ** 32))
                    nxt = np.array([rng.choice(len(row), p=row)
                                    for row in p], np.int64)
                else:
                    nxt = logits.argmax(-1).astype(np.int64)
                if eos_token_id is not None:
                    nxt = np.where(finished, eos_token_id, nxt)
                    finished |= nxt == eos_token_id
                out.append(nxt)
                if eos_token_id is not None and finished.all():
                    break
                step_ids = Tensor(jnp.asarray(nxt[:, None], jnp.int32))
                hidden, cache = self.transformer(
                    step_ids, cache=cache,
                    position_offset=prompt_len + i)
        finally:
            if was_training:
                self.train()
        return Tensor(jnp.asarray(np.stack(out, 1), jnp.int64))
