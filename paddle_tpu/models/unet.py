"""Stable-Diffusion-style conditional UNet (BASELINE config 5 workload).

Reference analog: the reference trains SD-UNet through PaddleMIX/ppdiffusers on
top of fleet recompute (fleet/recompute/recompute.py:463) + ZeRO-1 sharding
(dygraph_sharding_optimizer.py:54); the in-tree pieces it exercises are Conv2D,
GroupNorm, Silu, MultiHeadAttention and the recompute API.

TPU-first design decisions:
- NHWC layout throughout (TPU conv kernels want channels-last; XLA lowers
  NHWC convs straight onto the MXU without transposes).
- GroupNorm in fp32, convs/matmuls in the model dtype (bf16 on TPU).
- attention over flattened spatial tokens goes through
  F.scaled_dot_product_attention → the Pallas flash kernel.
- per-block ``recompute`` (jax.checkpoint) instead of a replay PyLayer.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp

from ..nn import (Layer, LayerList, Linear, Silu, GroupNorm, Conv2D, Dropout,
                  LayerNorm, Embedding)
from ..nn import functional as F
from ..core.tensor import Tensor, dispatch
from .. import ops


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    base_channels: int = 320
    channel_mult: tuple = (1, 2, 4, 4)
    layers_per_block: int = 2
    # levels (by index) with transformer blocks; SD-1.x puts cross-attention at
    # the three highest-resolution levels and none at the deepest (mid keeps it)
    attention_levels: tuple = (0, 1, 2)
    num_heads: int = 8
    context_dim: int = 768                # text-encoder hidden size
    transformer_depth: int = 1
    dropout: float = 0.0
    use_recompute: bool = False
    dtype: str = "float32"

    @staticmethod
    def sd_unet(**over):
        """SD-1.x UNet: 859M params."""
        return UNetConfig(**over)

    @staticmethod
    def tiny(**over):
        return UNetConfig(**{**dict(base_channels=32, channel_mult=(1, 2),
                                    layers_per_block=1, attention_levels=(1,),
                                    num_heads=2, context_dim=32), **over})


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal timestep embedding, fp32 (matches DDPM/SD)."""
    def fn(tv):
        half = dim // 2
        freqs = jnp.exp(-math.log(max_period)
                        * jnp.arange(half, dtype=jnp.float32) / half)
        ang = tv.astype(jnp.float32)[:, None] * freqs[None, :]
        emb = jnp.concatenate([jnp.cos(ang), jnp.sin(ang)], axis=-1)
        if dim % 2:
            emb = jnp.pad(emb, ((0, 0), (0, 1)))
        return emb
    return dispatch(fn, (t,), {}, name="timestep_embedding")


class ResBlock(Layer):
    """GN→SiLU→conv ×2 with a time-embedding shift injected between them."""

    def __init__(self, in_ch, out_ch, temb_ch, dropout=0.0):
        super().__init__()
        self.norm1 = GroupNorm(32 if in_ch % 32 == 0 else in_ch, in_ch, data_format="NHWC")
        self.conv1 = Conv2D(in_ch, out_ch, 3, padding=1, data_format="NHWC")
        self.temb_proj = Linear(temb_ch, out_ch)
        self.norm2 = GroupNorm(32 if out_ch % 32 == 0 else out_ch, out_ch, data_format="NHWC")
        self.dropout = Dropout(dropout)
        self.conv2 = Conv2D(out_ch, out_ch, 3, padding=1, data_format="NHWC")
        self.skip = (Conv2D(in_ch, out_ch, 1, data_format="NHWC")
                     if in_ch != out_ch else None)
        self.act = Silu()

    def forward(self, x, temb):
        h = self.conv1(self.act(self.norm1(x)))
        h = h + self.temb_proj(self.act(temb)).unsqueeze(1).unsqueeze(1)
        h = self.conv2(self.dropout(self.act(self.norm2(h))))
        return h + (self.skip(x) if self.skip is not None else x)


class CrossAttention(Layer):
    def __init__(self, query_dim, context_dim, num_heads):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = query_dim // num_heads
        self.to_q = Linear(query_dim, query_dim, bias_attr=False)
        self.to_k = Linear(context_dim, query_dim, bias_attr=False)
        self.to_v = Linear(context_dim, query_dim, bias_attr=False)
        self.to_out = Linear(query_dim, query_dim)

    def forward(self, x, context=None):
        context = x if context is None else context
        b, n, _ = x.shape
        m = context.shape[1]
        q = self.to_q(x).reshape([b, n, self.num_heads, self.head_dim])
        k = self.to_k(context).reshape([b, m, self.num_heads, self.head_dim])
        v = self.to_v(context).reshape([b, m, self.num_heads, self.head_dim])
        o = F.scaled_dot_product_attention(q, k, v, is_causal=False)
        return self.to_out(o.reshape([b, n, self.num_heads * self.head_dim]))


class GEGLU(Layer):
    def __init__(self, dim, inner):
        super().__init__()
        self.proj = Linear(dim, inner * 2)

    def forward(self, x):
        h = self.proj(x)
        a, g = ops.chunk(h, 2, axis=-1)
        return a * F.gelu(g)


class TransformerBlock(Layer):
    """Self-attn → cross-attn(context) → GEGLU FF, pre-LN (SD BasicTransformerBlock)."""

    def __init__(self, dim, context_dim, num_heads):
        super().__init__()
        self.norm1 = LayerNorm(dim)
        self.attn1 = CrossAttention(dim, dim, num_heads)
        self.norm2 = LayerNorm(dim)
        self.attn2 = CrossAttention(dim, context_dim, num_heads)
        self.norm3 = LayerNorm(dim)
        self.ff = GEGLU(dim, dim * 4)
        self.ff_out = Linear(dim * 4, dim)

    def forward(self, x, context):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        x = x + self.ff_out(self.ff(self.norm3(x)))
        return x


class SpatialTransformer(Layer):
    """GN → 1x1 in-proj → transformer over HW tokens → 1x1 out-proj + residual."""

    def __init__(self, channels, context_dim, num_heads, depth=1):
        super().__init__()
        self.norm = GroupNorm(32 if channels % 32 == 0 else channels, channels, data_format="NHWC")
        self.proj_in = Linear(channels, channels)
        self.blocks = LayerList([TransformerBlock(channels, context_dim, num_heads)
                                 for _ in range(depth)])
        self.proj_out = Linear(channels, channels)

    def forward(self, x, context):
        b, h, w, c = x.shape
        t = self.proj_in(self.norm(x).reshape([b, h * w, c]))
        for blk in self.blocks:
            t = blk(t, context)
        return x + self.proj_out(t).reshape([b, h, w, c])


class Downsample(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = Conv2D(ch, ch, 3, stride=2, padding=1, data_format="NHWC")

    def forward(self, x):
        return self.conv(x)


class Upsample2x(Layer):
    def __init__(self, ch):
        super().__init__()
        self.conv = Conv2D(ch, ch, 3, padding=1, data_format="NHWC")

    def forward(self, x):
        b, h, w, c = x.shape
        x = F.interpolate(x, size=(h * 2, w * 2), mode="nearest",
                          data_format="NHWC")
        return self.conv(x)


class UNetModel(Layer):
    """Conditional UNet ε-predictor. Input NHWC latents + timestep + context."""

    def __init__(self, config: UNetConfig):
        super().__init__()
        self.config = cfg = config
        ch = cfg.base_channels
        temb_ch = ch * 4
        self.time_mlp1 = Linear(ch, temb_ch)
        self.time_mlp2 = Linear(temb_ch, temb_ch)
        self.act = Silu()
        self.conv_in = Conv2D(cfg.in_channels, ch, 3, padding=1,
                              data_format="NHWC")

        # --- down path
        self.down_res = LayerList()
        self.down_attn = LayerList()
        self.downsamplers = LayerList()
        self._down_plan = []            # (n_res, has_attn, has_down) per level
        skip_chs = [ch]
        cur = ch
        n_levels = len(cfg.channel_mult)
        for lvl, mult in enumerate(cfg.channel_mult):
            out_ch = ch * mult
            has_attn = lvl in cfg.attention_levels
            for _ in range(cfg.layers_per_block):
                self.down_res.append(ResBlock(cur, out_ch, temb_ch, cfg.dropout))
                if has_attn:
                    self.down_attn.append(SpatialTransformer(
                        out_ch, cfg.context_dim, cfg.num_heads,
                        cfg.transformer_depth))
                cur = out_ch
                skip_chs.append(cur)
            has_down = lvl != n_levels - 1
            if has_down:
                self.downsamplers.append(Downsample(cur))
                skip_chs.append(cur)
            self._down_plan.append((cfg.layers_per_block, has_attn, has_down))

        # --- middle
        self.mid_res1 = ResBlock(cur, cur, temb_ch, cfg.dropout)
        self.mid_attn = SpatialTransformer(cur, cfg.context_dim, cfg.num_heads,
                                           cfg.transformer_depth)
        self.mid_res2 = ResBlock(cur, cur, temb_ch, cfg.dropout)

        # --- up path (mirror, consumes skips)
        self.up_res = LayerList()
        self.up_attn = LayerList()
        self.upsamplers = LayerList()
        self._up_plan = []
        for lvl in reversed(range(n_levels)):
            out_ch = ch * cfg.channel_mult[lvl]
            has_attn = lvl in cfg.attention_levels
            for _ in range(cfg.layers_per_block + 1):
                self.up_res.append(
                    ResBlock(cur + skip_chs.pop(), out_ch, temb_ch, cfg.dropout))
                if has_attn:
                    self.up_attn.append(SpatialTransformer(
                        out_ch, cfg.context_dim, cfg.num_heads,
                        cfg.transformer_depth))
                cur = out_ch
            has_up = lvl != 0
            if has_up:
                self.upsamplers.append(Upsample2x(cur))
            self._up_plan.append((cfg.layers_per_block + 1, has_attn, has_up))

        self.norm_out = GroupNorm(32 if cur % 32 == 0 else cur, cur, data_format="NHWC")
        self.conv_out = Conv2D(cur, cfg.out_channels, 3, padding=1,
                               data_format="NHWC")

    def _maybe_recompute(self, fn, *args):
        if self.config.use_recompute and self.training:
            from ..distributed.fleet.recompute import recompute
            return recompute(fn, *args)
        return fn(*args)

    def forward(self, x, timesteps, context):
        """x: (B,H,W,Cin) latents; timesteps: (B,); context: (B,L,context_dim)."""
        temb = timestep_embedding(timesteps, self.config.base_channels)
        # the sinusoidal embedding is fp32 by construction; match the model
        # dtype so a bf16 UNet doesn't silently promote the whole residual
        # stream (and every conv input) to fp32
        temb = temb.astype(self.time_mlp1.weight.dtype)
        temb = self.time_mlp2(self.act(self.time_mlp1(temb)))

        h = self.conv_in(x)
        skips = [h]
        ri = ai = di = 0
        for (n_res, has_attn, has_down) in self._down_plan:
            for _ in range(n_res):
                res, ri = self.down_res[ri], ri + 1
                if has_attn:
                    attn, ai = self.down_attn[ai], ai + 1
                    h = self._maybe_recompute(
                        lambda hh, tt, cc, _r=res, _a=attn:
                            _a(_r(hh, tt), cc), h, temb, context)
                else:
                    h = self._maybe_recompute(
                        lambda hh, tt, _r=res: _r(hh, tt), h, temb)
                skips.append(h)
            if has_down:
                ds, di = self.downsamplers[di], di + 1
                h = ds(h)
                skips.append(h)

        h = self._maybe_recompute(
            lambda hh, tt, cc: self.mid_res2(
                self.mid_attn(self.mid_res1(hh, tt), cc), tt),
            h, temb, context)

        ri = ai = ui = 0
        for (n_res, has_attn, has_up) in self._up_plan:
            for _ in range(n_res):
                res, ri = self.up_res[ri], ri + 1
                h = ops.concat([h, skips.pop()], axis=-1)
                if has_attn:
                    attn, ai = self.up_attn[ai], ai + 1
                    h = self._maybe_recompute(
                        lambda hh, tt, cc, _r=res, _a=attn:
                            _a(_r(hh, tt), cc), h, temb, context)
                else:
                    h = self._maybe_recompute(
                        lambda hh, tt, _r=res: _r(hh, tt), h, temb)
            if has_up:
                up, ui = self.upsamplers[ui], ui + 1
                h = up(h)

        return self.conv_out(self.act(self.norm_out(h)))


def sd_unet(**over):
    return UNetModel(UNetConfig.sd_unet(**over))


def diffusion_loss(model, latents, timesteps, context, noise, alphas_cumprod):
    """ε-prediction MSE: noise the latents with the closed-form q(x_t|x_0) and
    regress the added noise (DDPM objective used for SD training)."""
    a = ops.gather(alphas_cumprod, timesteps)
    # noise schedule stays fp32; the noised latents re-enter the model in its
    # own dtype (a bf16 UNet must not see an fp32-promoted input)
    sqrt_a = ops.sqrt(a).reshape([-1, 1, 1, 1])
    sqrt_1ma = ops.sqrt(1.0 - a).reshape([-1, 1, 1, 1])
    noisy = (latents * sqrt_a + noise * sqrt_1ma).astype(latents.dtype)
    pred = model(noisy, timesteps, context)
    return ((pred.astype("float32") - noise.astype("float32")) ** 2).mean()
