"""BERT (BASELINE config 2: BERT-base MLM pretraining, DP-only).

Reference analog: PaddleNLP BERT on paddle.nn.TransformerEncoder.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..nn import (
    Layer, Linear, Embedding, LayerNorm, Dropout, TransformerEncoder,
    TransformerEncoderLayer, Tanh, GELU,
)
from ..nn import functional as F
from ..core.tensor import Tensor
from .. import ops


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    # activation rematerialization (jax.checkpoint with RNG replay).
    # SELECTIVE: recompute_layers caps remat to the first k encoder layers
    # — bert is COMPUTE-bound, so full remat costs exactly the +1/3 step
    # FLOPs (measured 50.7 -> 38.0% MFU); remat of just enough layers
    # shaves the compile-time temp peak that made batch-96 OOM
    # nondeterministically while paying only k/num_layers of that
    # (VERDICT r4 #2)
    use_recompute: bool = False
    recompute_layers: int | None = None
    # jax checkpoint policy name (distributed/fleet/recompute.py POLICIES):
    # "dots_saveable" keeps matmul outputs and recomputes only elementwise
    recompute_policy: str | None = None
    # chunked fused (decoder matmul + CE) head: never materializes the
    # full [tokens, vocab] logits (+grad) — the largest single activation
    # of the MLM step (~6 GB at batch 96) and the tensor whose scheduling
    # made the B=96 compile OOM nondeterministically. Costs one extra
    # head-matmul pass in backward (~+6% step FLOPs for bert-base).
    # CONTRACT: with labels, forward returns (loss, logits) on the
    # unfused path but (loss, <FusedLogitsUnavailable>) under this flag —
    # the placeholder is falsy and raises a RuntimeError naming the flag
    # if consumed (models/common.py). Callers needing logits must run
    # unfused or call without labels.
    fuse_mlm_head_ce: bool = False

    @staticmethod
    def base(**over):
        return BertConfig(**over)

    @staticmethod
    def tiny(**over):
        return BertConfig(**{**dict(vocab_size=1024, hidden_size=128,
                                    num_hidden_layers=2, num_attention_heads=4,
                                    intermediate_size=256,
                                    max_position_embeddings=128), **over})


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = Embedding(c.max_position_embeddings, c.hidden_size)
        self.token_type_embeddings = Embedding(c.type_vocab_size, c.hidden_size)
        self.layer_norm = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None):
        s = input_ids.shape[1]
        pos = ops.arange(s, dtype="int64")
        x = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is None:
            # reference semantics (HF/paddle): segment ids default to 0 — add
            # the broadcast type-0 row rather than gathering a [B,S] zeros map
            x = x + self.token_type_embeddings.weight[0]
        else:
            x = x + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(x))


class BertModel(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.config = c
        self.embeddings = BertEmbeddings(c)
        enc_layer = TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation="gelu",
            attn_dropout=c.attention_probs_dropout_prob,
            act_dropout=0.0, layer_norm_eps=c.layer_norm_eps)
        self.encoder = TransformerEncoder(
            enc_layer, c.num_hidden_layers,
            use_recompute=c.use_recompute,
            recompute_layers=c.recompute_layers,
            recompute_policy=c.recompute_policy)
        self.pooler = Linear(c.hidden_size, c.hidden_size)
        self.pooler_act = Tanh()

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [B, S] 1/0 -> additive [B, 1, 1, S]
            am = ops.unsqueeze(attention_mask, [1, 2])
            am = (1.0 - am.astype("float32")) * -1e9
        else:
            am = None
        x = self.encoder(x, am)
        pooled = self.pooler_act(self.pooler(x[:, 0]))
        return x, pooled


class BertForMaskedLM(Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        c = config
        self.config = c
        self.bert = BertModel(c)
        self.transform = Linear(c.hidden_size, c.hidden_size)
        self.transform_act = GELU()
        self.transform_norm = LayerNorm(c.hidden_size, c.layer_norm_eps)
        self.decoder = Linear(c.hidden_size, c.vocab_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_norm(self.transform_act(self.transform(seq)))
        if labels is not None and self.config.fuse_mlm_head_ce:
            # chunked fused head: loss computed without the full logits
            # tensor; mean over non-ignored positions matches
            # cross_entropy(reduction='mean', ignore_index=-100)
            from ..ops.kernels.fused_ce import fused_linear_ce
            from ..core.tensor import dispatch

            def fn(h2, w, b, lbl):
                import jax.numpy as jnp
                flat = fused_linear_ce(h2, w, b, lbl, -100)
                n_valid = jnp.maximum(jnp.sum(lbl != -100), 1)
                return jnp.sum(flat) / n_valid.astype(jnp.float32)

            loss = dispatch(
                fn,
                (ops.reshape(h, [-1, self.config.hidden_size]),
                 self.decoder.weight, self.decoder.bias,
                 ops.reshape(labels, [-1])), {}, name="fused_linear_ce")
            from .common import FusedLogitsUnavailable
            return loss, FusedLogitsUnavailable("fuse_mlm_head_ce")
        logits = self.decoder(h)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            # no fp32 pre-cast: cross_entropy's fused path accumulates
            # the lse in fp32 internally without copying the logits
            ops.reshape(logits, [-1, self.config.vocab_size]),
            ops.reshape(labels, [-1]), ignore_index=-100)
        return loss, logits
