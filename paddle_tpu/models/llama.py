"""Llama-family decoder LM — the flagship/north-star model.

Reference analog: the reference trains Llama through PaddleNLP on top of fleet TP
layers + flash-attn + fused rope/rms kernels
(test/auto_parallel/hybrid_strategy/semi_auto_llama.py is the in-tree config).

TPU-first design decisions:
- bf16 weights + fp32 RMSNorm accumulation (MXU-native dtypes)
- attention through F.scaled_dot_product_attention → Pallas flash kernel on TPU
- rope applied in fp32 with precomputed cos/sin cache (fused by XLA)
- mesh sharding annotations live OUTSIDE the model (distributed.shard_llama applies
  GSPMD NamedShardings over a dp/tp mesh) so the same module runs 1-chip or pod.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from ..nn import Layer, Linear, Embedding, RMSNorm, LayerList
from ..nn import functional as F
from ..core.tensor import Tensor, dispatch
from .. import ops


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    # PaddleNLP-style horizontal fusion: one QKV GEMM / one gate+up GEMM so
    # the layer input is read once per block instead of 3x/2x (HBM win)
    fuse_attention_qkv: bool = False
    fuse_swiglu: bool = False
    # per-decoder-layer activation recompute (reference: PaddleNLP llama
    # use_recompute → fleet recompute per block). Saves only each block's
    # input; XLA re-traces the block inside the backward.
    use_recompute: bool = False
    recompute_policy: str | None = None
    dtype: str = "float32"

    @staticmethod
    def llama2_7b(**over):
        return LlamaConfig(**{**dict(hidden_size=4096, intermediate_size=11008,
                                     num_hidden_layers=32, num_attention_heads=32),
                              **over})

    @staticmethod
    def tiny(**over):
        return LlamaConfig(**{**dict(vocab_size=1024, hidden_size=128,
                                     intermediate_size=352, num_hidden_layers=2,
                                     num_attention_heads=4, num_key_value_heads=4,
                                     max_position_embeddings=256), **over})


def precompute_rope(head_dim, max_len, theta=10000.0):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                      # [T, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)      # [T, D]
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(q, k, cos, sin, position_offset=0):
    """q,k: [B, S, H, D]; rotate-half formulation in fp32."""
    s = q.shape[1]
    cos_t = jax.lax.dynamic_slice_in_dim(cos, position_offset, s, 0)[None, :, None, :]
    sin_t = jax.lax.dynamic_slice_in_dim(sin, position_offset, s, 0)[None, :, None, :]

    def rot(x):
        x32 = x.astype(jnp.float32)
        half = x.shape[-1] // 2
        x1, x2 = x32[..., :half], x32[..., half:]
        rotated = jnp.concatenate([-x2, x1], axis=-1)
        return (x32 * cos_t + rotated * sin_t).astype(x.dtype)
    return rot(q), rot(k)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.fused = bool(getattr(c, "fuse_attention_qkv", False))
        if self.fused:
            self.qkv_proj = Linear(
                c.hidden_size,
                (self.num_heads + 2 * self.num_kv_heads) * self.head_dim,
                bias_attr=False)
        else:
            self.q_proj = Linear(c.hidden_size, self.num_heads * self.head_dim,
                                 bias_attr=False)
            self.k_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
            self.v_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, c.hidden_size,
                             bias_attr=False)
        self.config = c

    def forward(self, x, rope_cache, attn_mask=None, kv_cache=None, position_offset=0):
        b, s = x.shape[0], x.shape[1]
        if self.fused:
            qkv = self.qkv_proj(x)
            nq = self.num_heads * self.head_dim
            nkv = self.num_kv_heads * self.head_dim
            q = ops.reshape(qkv[:, :, :nq],
                            [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(qkv[:, :, nq:nq + nkv],
                            [b, s, self.num_kv_heads, self.head_dim])
            v = ops.reshape(qkv[:, :, nq + nkv:],
                            [b, s, self.num_kv_heads, self.head_dim])
        else:
            q = ops.reshape(self.q_proj(x),
                            [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(self.k_proj(x),
                            [b, s, self.num_kv_heads, self.head_dim])
            v = ops.reshape(self.v_proj(x),
                            [b, s, self.num_kv_heads, self.head_dim])
        cos, sin = rope_cache
        q, k = dispatch(lambda qq, kk: apply_rope(qq, kk, cos, sin, position_offset),
                        (q, k), {}, name="rope")
        if kv_cache is not None:
            k = ops.concat([kv_cache[0], k], axis=1)
            v = ops.concat([kv_cache[1], v], axis=1)
            kv_cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=(attn_mask is None),
            training=self.training)
        out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        return (out, kv_cache) if kv_cache is not None else out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.fused = bool(getattr(c, "fuse_swiglu", False))
        if self.fused:
            self.gate_up_proj = Linear(c.hidden_size, 2 * c.intermediate_size,
                                       bias_attr=False)
        else:
            self.gate_proj = Linear(c.hidden_size, c.intermediate_size,
                                    bias_attr=False)
            self.up_proj = Linear(c.hidden_size, c.intermediate_size,
                                  bias_attr=False)
        self.down_proj = Linear(c.intermediate_size, c.hidden_size, bias_attr=False)
        self._ff = c.intermediate_size

    def forward(self, x):
        if self.fused:
            gu = self.gate_up_proj(x)
            return self.down_proj(F.swiglu(gu[:, :, :self._ff],
                                           gu[:, :, self._ff:]))
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, x, rope_cache, attn_mask=None):
        x = x + self.self_attn(self.input_layernorm(x), rope_cache, attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size)
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = precompute_rope(head_dim, config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None):
        x = self.embed_tokens(input_ids)
        rope = (self.rope_cos._value, self.rope_sin._value)
        remat = self.config.use_recompute and self.training
        if remat:
            from ..distributed.fleet.recompute import recompute
        for layer in self.layers:
            if remat:
                x = recompute(layer, x, rope, attn_mask,
                              checkpoint_policy=self.config.recompute_policy)
            else:
                x = layer(x, rope, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.llama(input_ids, attn_mask)
        if self.config.tie_word_embeddings:
            logits = ops.matmul(hidden, self.llama.embed_tokens.weight,
                                transpose_y=True)
        else:
            logits = self.lm_head(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            # no fp32 pre-cast: cross_entropy's fused path accumulates
            # the lse in fp32 internally without copying the logits
            ops.reshape(logits, [-1, self.config.vocab_size]),
            ops.reshape(labels, [-1]), ignore_index=-100)
        return loss, logits

    def flops_per_token(self, seq_len):
        """Model FLOPs per token (fwd+bwd 3x fwd) for MFU accounting."""
        c = self.config
        d, L = c.hidden_size, c.num_hidden_layers
        ff = c.intermediate_size
        per_layer = (
            2 * d * d * (1 + 2 * c.num_key_value_heads / c.num_attention_heads + 1)
            + 2 * 2 * d * seq_len / 2  # attention scores+values (causal half)
            + 2 * 3 * d * ff
        )
        embed = 2 * d * c.vocab_size
        return 3 * (L * per_layer + embed)
