"""Llama-family decoder LM — the flagship/north-star model.

Reference analog: the reference trains Llama through PaddleNLP on top of fleet TP
layers + flash-attn + fused rope/rms kernels
(test/auto_parallel/hybrid_strategy/semi_auto_llama.py is the in-tree config).

TPU-first design decisions:
- bf16 weights + fp32 RMSNorm accumulation (MXU-native dtypes)
- attention through F.scaled_dot_product_attention → Pallas flash kernel on TPU
- rope applied in fp32 with precomputed cos/sin cache (fused by XLA)
- mesh sharding annotations live OUTSIDE the model (distributed.shard_llama applies
  GSPMD NamedShardings over a dp/tp mesh) so the same module runs 1-chip or pod.
"""
from __future__ import annotations

import math

import numpy as np
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from ..nn import Layer, Linear, Embedding, RMSNorm, LayerList
from ..nn import functional as F
from ..core.tensor import Tensor, dispatch
from .. import ops


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    # PaddleNLP-style horizontal fusion: one QKV GEMM / one gate+up GEMM so
    # the layer input is read once per block instead of 3x/2x (HBM win)
    fuse_attention_qkv: bool = False
    fuse_swiglu: bool = False
    # per-decoder-layer activation recompute (reference: PaddleNLP llama
    # use_recompute → fleet recompute per block). Saves only each block's
    # input; XLA re-traces the block inside the backward.
    use_recompute: bool = False
    recompute_policy: str | None = None
    dtype: str = "float32"

    @staticmethod
    def llama2_7b(**over):
        return LlamaConfig(**{**dict(hidden_size=4096, intermediate_size=11008,
                                     num_hidden_layers=32, num_attention_heads=32),
                              **over})

    @staticmethod
    def tiny(**over):
        return LlamaConfig(**{**dict(vocab_size=1024, hidden_size=128,
                                     intermediate_size=352, num_hidden_layers=2,
                                     num_attention_heads=4, num_key_value_heads=4,
                                     max_position_embeddings=256), **over})


def precompute_rope(head_dim, max_len, theta=10000.0):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                      # [T, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)      # [T, D]
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(q, k, cos, sin, position_offset=0):
    """q,k: [B, S, H, D]; rotate-half formulation in fp32."""
    s = q.shape[1]
    cos_t = jax.lax.dynamic_slice_in_dim(cos, position_offset, s, 0)[None, :, None, :]
    sin_t = jax.lax.dynamic_slice_in_dim(sin, position_offset, s, 0)[None, :, None, :]

    def rot(x):
        x32 = x.astype(jnp.float32)
        half = x.shape[-1] // 2
        x1, x2 = x32[..., :half], x32[..., half:]
        rotated = jnp.concatenate([-x2, x1], axis=-1)
        return (x32 * cos_t + rotated * sin_t).astype(x.dtype)
    return rot(q), rot(k)


class StaticKVCache:
    """Fixed-capacity per-layer KV cache for decoding: buffers preallocated
    at the FINAL sequence length and written in place with
    dynamic_update_slice. Together with a traced position offset, every
    decode step then has static shapes — ONE compiled program serves the
    whole generation instead of one per token per layer (the concat-grown
    tuple cache changes the k/v length every step)."""

    __slots__ = ("k", "v")

    def __init__(self, k, v):
        self.k, self.v = k, v


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.fused = bool(getattr(c, "fuse_attention_qkv", False))
        if self.fused:
            self.qkv_proj = Linear(
                c.hidden_size,
                (self.num_heads + 2 * self.num_kv_heads) * self.head_dim,
                bias_attr=False)
        else:
            self.q_proj = Linear(c.hidden_size, self.num_heads * self.head_dim,
                                 bias_attr=False)
            self.k_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
            self.v_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, c.hidden_size,
                             bias_attr=False)
        self.config = c

    def forward(self, x, rope_cache, attn_mask=None, kv_cache=None, position_offset=0):
        b, s = x.shape[0], x.shape[1]
        if self.fused:
            qkv = self.qkv_proj(x)
            nq = self.num_heads * self.head_dim
            nkv = self.num_kv_heads * self.head_dim
            q = ops.reshape(qkv[:, :, :nq],
                            [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(qkv[:, :, nq:nq + nkv],
                            [b, s, self.num_kv_heads, self.head_dim])
            v = ops.reshape(qkv[:, :, nq + nkv:],
                            [b, s, self.num_kv_heads, self.head_dim])
        else:
            q = ops.reshape(self.q_proj(x),
                            [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(self.k_proj(x),
                            [b, s, self.num_kv_heads, self.head_dim])
            v = ops.reshape(self.v_proj(x),
                            [b, s, self.num_kv_heads, self.head_dim])
        cos, sin = rope_cache
        if isinstance(position_offset, Tensor):
            # traced offset (static-shape decode): the offset is a dispatch
            # ARGUMENT, so every step shares one compiled entry
            q, k = dispatch(
                lambda qq, kk, off: apply_rope(qq, kk, cos, sin,
                                               off.astype(jnp.int32)),
                (q, k, position_offset), {}, name="rope_offset")
        else:
            q, k = dispatch(
                lambda qq, kk: apply_rope(qq, kk, cos, sin, position_offset),
                (q, k), {}, name="rope")
        if isinstance(kv_cache, StaticKVCache):
            def upd(buf, new, off):
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, new.astype(buf.dtype), off.astype(jnp.int32), 1)

            k_buf = dispatch(upd, (kv_cache.k, k, position_offset), {},
                             name="kv_update")
            v_buf = dispatch(upd, (kv_cache.v, v, position_offset), {},
                             name="kv_update")
            T = k_buf.shape[1]

            def make_mask(off):
                last = off.astype(jnp.int32) + jnp.int32(s - 1)
                valid = jnp.arange(T, dtype=jnp.int32)[None, None, None, :] \
                    <= last
                return jnp.where(valid, jnp.float32(0), jnp.float32(-1e30))

            mask = dispatch(make_mask, (position_offset,), {},
                            name="kv_decode_mask")
            out = F.scaled_dot_product_attention(
                q, k_buf, v_buf, attn_mask=mask, is_causal=False,
                training=self.training)
            out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
            return self.o_proj(out), StaticKVCache(k_buf, v_buf)
        if kv_cache is not None:
            k = ops.concat([kv_cache[0], k], axis=1)
            v = ops.concat([kv_cache[1], v], axis=1)
            kv_cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=(attn_mask is None),
            training=self.training)
        out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        return (out, kv_cache) if kv_cache is not None else out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.fused = bool(getattr(c, "fuse_swiglu", False))
        if self.fused:
            self.gate_up_proj = Linear(c.hidden_size, 2 * c.intermediate_size,
                                       bias_attr=False)
        else:
            self.gate_proj = Linear(c.hidden_size, c.intermediate_size,
                                    bias_attr=False)
            self.up_proj = Linear(c.hidden_size, c.intermediate_size,
                                  bias_attr=False)
        self.down_proj = Linear(c.intermediate_size, c.hidden_size, bias_attr=False)
        self._ff = c.intermediate_size

    def forward(self, x):
        if self.fused:
            gu = self.gate_up_proj(x)
            return self.down_proj(F.swiglu(gu[:, :, :self._ff],
                                           gu[:, :, self._ff:]))
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, x, rope_cache, attn_mask=None, kv_cache=None,
                position_offset=0):
        if kv_cache is not None:
            attn_out, new_cache = self.self_attn(
                self.input_layernorm(x), rope_cache, attn_mask, kv_cache,
                position_offset)
            x = x + attn_out
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), rope_cache, attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size)
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = precompute_rope(head_dim, config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None, kv_caches=None,
                position_offset=0):
        x = self.embed_tokens(input_ids)
        rope = (self.rope_cos._value, self.rope_sin._value)
        if kv_caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, kv_caches):
                x, c = layer(x, rope, attn_mask, cache, position_offset)
                new_caches.append(c)
            return self.norm(x), new_caches
        remat = self.config.use_recompute and self.training
        if remat:
            from ..distributed.fleet.recompute import recompute
        for layer in self.layers:
            if remat:
                x = recompute(layer, x, rope, attn_mask,
                              checkpoint_policy=self.config.recompute_policy)
            else:
                x = layer(x, rope, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            return ops.matmul(hidden, self.llama.embed_tokens.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.llama(input_ids, attn_mask)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            # no fp32 pre-cast: cross_entropy's fused path accumulates
            # the lse in fp32 internally without copying the logits
            ops.reshape(logits, [-1, self.config.vocab_size]),
            ops.reshape(labels, [-1]), ignore_index=-100)
        return loss, logits

    @staticmethod
    def _sample(logits_np, temperature, top_k, top_p, rng):
        if temperature <= 0.0:
            return np.argmax(logits_np, axis=-1)
        logits_np = logits_np / temperature
        out = np.empty(logits_np.shape[0], np.int64)
        for b in range(logits_np.shape[0]):
            row = logits_np[b]
            if top_k and top_k > 0:
                tk = min(int(top_k), len(row))
                kth = np.partition(row, -tk)[-tk]
                row = np.where(row < kth, -np.inf, row)
            probs = np.exp(row - row.max())
            probs = probs / probs.sum()
            if top_p and top_p < 1.0:
                order = np.argsort(-probs)
                cum = np.cumsum(probs[order])
                cut = np.searchsorted(cum, top_p) + 1
                mask = np.zeros_like(probs)
                mask[order[:cut]] = 1.0
                probs = probs * mask
                probs = probs / probs.sum()
            out[b] = rng.choice(len(probs), p=probs)
        return out

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, top_p=1.0, eos_token_id=None):
        """Autoregressive decoding with a per-layer KV cache (reference
        surface: paddlenlp GenerationMixin.generate; the reference keeps it
        out-of-tree, the flagship model here ships it in-core).

        Prefill runs the full prompt once (flash-attention path, causal);
        decode steps feed ONE token against a fixed-capacity
        :class:`StaticKVCache` with a TRACED position offset — every step
        has identical shapes, so the whole generation runs through one
        compiled program per op (no per-token recompiles). Attention over
        the padded cache is masked to the valid prefix.
        temperature<=0 = greedy; top_k/top_p sampling draws from the
        framework RNG (``paddle.seed``-deterministic). Decoding is capped
        at ``max_position_embeddings`` (the rope table's end) with a
        warning.
        """
        from ..core import random as _random
        from ..core.tensor import no_grad
        import jax.numpy as jnp

        c = self.config
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(np.asarray(input_ids), jnp.int32))
        B, prompt_len = ids.shape[0], ids.shape[1]
        if prompt_len >= c.max_position_embeddings:
            raise ValueError(
                f"prompt length {prompt_len} >= max_position_embeddings "
                f"{c.max_position_embeddings}: no positions left to decode")
        limit = min(int(max_new_tokens),
                    c.max_position_embeddings - prompt_len)
        if limit < int(max_new_tokens):
            import warnings
            warnings.warn(
                f"generate: capping max_new_tokens {max_new_tokens} -> "
                f"{limit} (rope table ends at position "
                f"{c.max_position_embeddings})", RuntimeWarning,
                stacklevel=2)
        if limit <= 0:
            return Tensor(jnp.zeros((B, 0), jnp.int64))
        total = prompt_len + limit
        head_dim = c.hidden_size // c.num_attention_heads
        dt = self.llama.embed_tokens.weight.dtype
        empty = [(Tensor(jnp.zeros((B, 0, c.num_key_value_heads, head_dim),
                                   dt)),
                  Tensor(jnp.zeros((B, 0, c.num_key_value_heads, head_dim),
                                   dt)))
                 for _ in range(c.num_hidden_layers)]
        seed, counter = _random.default_generator.next_seed()
        rng = np.random.default_rng((seed, counter))

        was_training = self.training
        self.eval()
        try:
            with no_grad():
                # prefill: one causal pass over the whole prompt (flash
                # path), then pad each layer's cache to the FINAL length so
                # all decode steps share static shapes (StaticKVCache)
                hidden, grown = self.llama(ids, kv_caches=empty,
                                           position_offset=0)

                def to_static(t):
                    pad = total - t.shape[1]
                    return Tensor(jnp.pad(
                        t._value, ((0, 0), (0, pad), (0, 0), (0, 0))))

                caches = [StaticKVCache(to_static(k), to_static(v))
                          for k, v in grown]
                generated = []
                cur_len = prompt_len
                last_h = hidden[:, -1:]
                finished = np.zeros(B, bool)
                for _ in range(limit):
                    logits = self._logits(last_h)
                    nxt = self._sample(
                        np.asarray(logits._value[:, 0]).astype(np.float32),
                        temperature, top_k, top_p, rng)
                    if eos_token_id is not None:
                        nxt = np.where(finished, eos_token_id, nxt)
                        finished |= (nxt == eos_token_id)
                    generated.append(nxt)
                    if eos_token_id is not None and finished.all():
                        break
                    if cur_len >= total:
                        break
                    tok = Tensor(jnp.asarray(nxt[:, None], jnp.int32))
                    # traced offset: the decode program is keyed on shapes
                    # only — step 2 onward hits the compiled dispatch cache
                    off = Tensor(jnp.asarray(cur_len, jnp.int32))
                    last_h, caches = self.llama(
                        tok, kv_caches=caches, position_offset=off)
                    cur_len += 1
        finally:
            if was_training:
                self.train()
        out = np.stack(generated, axis=1)
        return Tensor(jnp.asarray(out, jnp.int64))

    def flops_per_token(self, seq_len):
        """Model FLOPs per token (fwd+bwd 3x fwd) for MFU accounting."""
        c = self.config
        d, L = c.hidden_size, c.num_hidden_layers
        ff = c.intermediate_size
        per_layer = (
            2 * d * d * (1 + 2 * c.num_key_value_heads / c.num_attention_heads + 1)
            + 2 * 2 * d * seq_len / 2  # attention scores+values (causal half)
            + 2 * 3 * d * ff
        )
        embed = 2 * d * c.vocab_size
        return 3 * (L * per_layer + embed)
