"""Llama-family decoder LM — the flagship/north-star model.

Reference analog: the reference trains Llama through PaddleNLP on top of fleet TP
layers + flash-attn + fused rope/rms kernels
(test/auto_parallel/hybrid_strategy/semi_auto_llama.py is the in-tree config).

TPU-first design decisions:
- bf16 weights + fp32 RMSNorm accumulation (MXU-native dtypes)
- attention through F.scaled_dot_product_attention → Pallas flash kernel on TPU
- rope applied in fp32 with precomputed cos/sin cache (fused by XLA)
- mesh sharding annotations live OUTSIDE the model (distributed.shard_llama applies
  GSPMD NamedShardings over a dp/tp mesh) so the same module runs 1-chip or pod.
"""
from __future__ import annotations

import math

import numpy as np
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from ..nn import Layer, Linear, Embedding, RMSNorm, LayerList
from ..nn import functional as F
from ..core.tensor import Tensor, dispatch, functional_mode
from .lora import active_lora
from .. import ops


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    # PaddleNLP-style horizontal fusion: one QKV GEMM / one gate+up GEMM so
    # the layer input is read once per block instead of 3x/2x (HBM win)
    fuse_attention_qkv: bool = False
    fuse_swiglu: bool = False
    # per-decoder-layer activation recompute (reference: PaddleNLP llama
    # use_recompute → fleet recompute per block). Saves only each block's
    # input; XLA re-traces the block inside the backward.
    use_recompute: bool = False
    recompute_policy: str | None = None
    dtype: str = "float32"

    @staticmethod
    def llama2_7b(**over):
        return LlamaConfig(**{**dict(hidden_size=4096, intermediate_size=11008,
                                     num_hidden_layers=32, num_attention_heads=32),
                              **over})

    @staticmethod
    def tiny(**over):
        return LlamaConfig(**{**dict(vocab_size=1024, hidden_size=128,
                                     intermediate_size=352, num_hidden_layers=2,
                                     num_attention_heads=4, num_key_value_heads=4,
                                     max_position_embeddings=256), **over})


#: Megatron TP placement plan for the llama stack (weights are [in, out]
#: like nn.Linear): column-parallel shards the output dim, row-parallel the
#: input dim, the vocab embedding its vocab dim. THE canonical table — the
#: 7B scale proofs, the pod-topology worker, and the sharded-generate tests
#: all consume it (reference: fleet mp_layers Column/RowParallelLinear as
#: applied in test/auto_parallel/hybrid_strategy/semi_auto_llama.py).
LLAMA_TP_RULES = (
    ("embed_tokens.weight", ("mp", None)),
    ("q_proj.weight", (None, "mp")),
    ("k_proj.weight", (None, "mp")),
    ("v_proj.weight", (None, "mp")),
    ("o_proj.weight", ("mp", None)),
    ("gate_proj.weight", (None, "mp")),
    ("up_proj.weight", (None, "mp")),
    ("down_proj.weight", ("mp", None)),
    ("lm_head.weight", (None, "mp")),
)


def llama_tp_spec(name, axis="mp"):
    """PartitionSpec for parameter ``name`` under LLAMA_TP_RULES (norms and
    everything unlisted: replicated).

    Weight-only quantized deploy params are covered too: a
    ``*.quant_weight`` keeps its base linear's [in, out] placement (the
    int4 packed in-dim shards the same way — each packed row holds two
    adjacent input features), and ``*.weight_scale`` ([out]) shards iff the
    base rule shards the out dim — otherwise a quantized model would
    silently replicate under TP."""
    from jax.sharding import PartitionSpec

    def expand(spec):
        return PartitionSpec(*[axis if s == "mp" else s for s in spec])

    for pat, spec in LLAMA_TP_RULES:
        if name.endswith(pat):
            return expand(spec)
        stem = pat[:-len(".weight")] if pat.endswith(".weight") else None
        if stem is not None:
            if name.endswith(stem + ".quant_weight"):
                return expand(spec)
            if name.endswith(stem + ".weight_scale"):
                return expand(spec[1:]) if spec[1] == "mp" \
                    else PartitionSpec()
    return PartitionSpec()


def precompute_rope(head_dim, max_len, theta=10000.0):
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                                / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)                      # [T, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)      # [T, D]
    return jnp.cos(emb), jnp.sin(emb)


def apply_rope(q, k, cos, sin, position_offset=0):
    """q,k: [B, S, H, D]; rotate-half formulation in fp32. position_offset is
    a scalar (shared offset) or a [B] vector (per-slot positions for the
    continuous-batching decode step)."""
    s = q.shape[1]
    if getattr(position_offset, "ndim", 0) == 1:
        pos = position_offset[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
        cos_t = jnp.take(cos, pos, axis=0)[:, :, None, :]   # [B, S, 1, D]
        sin_t = jnp.take(sin, pos, axis=0)[:, :, None, :]
    else:
        cos_t = jax.lax.dynamic_slice_in_dim(
            cos, position_offset, s, 0)[None, :, None, :]
        sin_t = jax.lax.dynamic_slice_in_dim(
            sin, position_offset, s, 0)[None, :, None, :]

    def rot(x):
        x32 = x.astype(jnp.float32)
        half = x.shape[-1] // 2
        x1, x2 = x32[..., :half], x32[..., half:]
        rotated = jnp.concatenate([-x2, x1], axis=-1)
        return (x32 * cos_t + rotated * sin_t).astype(x.dtype)
    return rot(q), rot(k)


class StaticKVCache:
    """Fixed-capacity per-layer KV cache for decoding: buffers preallocated
    at the FINAL sequence length and written in place with
    dynamic_update_slice. Together with a traced position offset, every
    decode step then has static shapes — ONE compiled program serves the
    whole generation instead of one per token per layer (the concat-grown
    tuple cache changes the k/v length every step)."""

    __slots__ = ("k", "v")

    def __init__(self, k, v):
        self.k, self.v = k, v


class SlotKVCache:
    """Static KV buffers with PER-SLOT lengths — the continuous-batching
    cache (:class:`paddle_tpu.inference.LLMEngine`): ``k``/``v`` are
    [B, capacity, H, D] slot buffers and ``lens`` [B] is how many tokens each
    slot has cached. A decode step writes slot b's new KV at position
    ``lens[b]`` and attends positions <= lens[b], so sequences of different
    lengths share ONE compiled step program. The engine, not the model,
    advances ``lens`` (only for slots that are active)."""

    __slots__ = ("k", "v", "lens")

    def __init__(self, k, v, lens):
        self.k, self.v, self.lens = k, v, lens


class PagedKVCache:
    """vLLM-style paged KV cache (reference:
    python/paddle/incubate/nn/functional/block_multihead_attention.py:1 —
    the phi block_multi_head_attention kernel's layout): physical pools
    ``k``/``v`` of shape [num_blocks, H, block_size, D], a per-sequence
    ``block_tables`` [B, max_blocks] mapping logical KV block -> physical
    block (-1 = unallocated), and ``seq_lens`` [B] tokens already cached.
    Decode steps attend through
    :func:`paddle_tpu.incubate.nn.functional.block_multihead_attention`.

    ``q_lens`` (the fused scheduler's mixed step): per-sequence count of
    REAL rows in an S>1 window — sequence b appends positions
    [seq_lens[b], seq_lens[b]+q_lens[b]) (a prefill chunk, one decode
    token, or 0 = idle slot; rows past q_lens are padding). Required for
    S>1; None keeps the one-token decode-step contract.

    ``quant`` + ``k_scale``/``v_scale`` (the engine's ``kv_cache_dtype``):
    the pools are int8/int4 QUANTIZED storage (int4 nibble-packed on the
    head dim) with per-(physical block, kv head) fp32 scale arrays
    [num_blocks, Hkv] riding alongside — the attention op dequantizes on
    read and returns updated scales with the pools."""

    __slots__ = ("k", "v", "block_tables", "seq_lens", "q_lens",
                 "k_scale", "v_scale", "quant")

    def __init__(self, k, v, block_tables, seq_lens, q_lens=None,
                 k_scale=None, v_scale=None, quant=None):
        self.k, self.v = k, v
        self.block_tables, self.seq_lens = block_tables, seq_lens
        self.q_lens = q_lens
        self.k_scale, self.v_scale = k_scale, v_scale
        self.quant = quant


class ChunkKVCache:
    """Dense slot buffers with per-slot APPEND windows — the fused
    prefill+decode scheduler's dense cache: ``k``/``v`` are [B, capacity,
    H, D] slot buffers, ``lens`` [B] tokens already cached, ``q_lens``
    [B] how many of the step's S rows are real for each slot. Row i of
    slot b writes position lens[b]+i when i < q_lens[b] (padding and
    past-capacity rows DROP — no dynamic-slice clamping that could slide
    back over live history) and attends causally to positions
    <= lens[b]+i. The engine advances ``lens`` by q_lens itself."""

    __slots__ = ("k", "v", "lens", "q_lens")

    def __init__(self, k, v, lens, q_lens):
        self.k, self.v, self.lens, self.q_lens = k, v, lens, q_lens


def _window_causal_mask(s, T):
    """Additive mask builder for a per-slot decode/append window: row i of
    slot b sits at absolute position lens[b]+i and may see positions
    <= lens[b]+i (cached history plus its own window prefix). THE one copy
    — the SlotKVCache and ChunkKVCache attention branches both dispatch
    it, so the sentinel/dtype can never diverge between the legacy slot
    path and the fused mixed step."""
    def mask_fn(lens):
        rows = lens.astype(jnp.int32)[:, None, None, None] + \
            jnp.arange(s, dtype=jnp.int32)[None, None, :, None]
        valid = jnp.arange(T, dtype=jnp.int32)[None, None, None, :] <= rows
        return jnp.where(valid, jnp.float32(0), jnp.float32(-1e30))
    return mask_fn


def _filter_logits(logits, temp_val, top_k, top_p_val, use_top_p=True):
    """THE temperature/top-k/top-p filter pipeline (temperature scale, then
    top-k cut, then the nucleus mass cut on the renormalized distribution).
    Single source consumed by the sampler below — which the serving
    engine's COUPLED speculative acceptance (inference/llm_engine.py
    ``verify_window``) also samples through, so speculative exactness
    rides on drafts being tested against exactly the distribution
    tokens are drawn from."""
    logits = logits.astype(jnp.float32) / temp_val.astype(jnp.float32)
    V = logits.shape[-1]
    if top_k and 0 < int(top_k) < V:
        # one O(V * k) top_k serves BOTH cuts: after the top-k mask, the
        # surviving distribution lives entirely in this sorted-descending
        # slice, so the nucleus cutoff computes over k entries instead of a
        # full O(V log V) sort of the 32k-vocab logits every sampled step.
        # Caveat: with EXACT ties at the k-th value the strict `< kth` mask
        # keeps all tied entries but the slice normalizes over exactly k —
        # a measure-zero divergence for real logits, accepted for the
        # per-step sort elimination
        vals = jax.lax.top_k(logits, int(top_k))[0]       # [..., k] desc
        kth = vals[..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
        if use_top_p:
            probs = jax.nn.softmax(vals, axis=-1)
            cum = jnp.cumsum(probs, axis=-1)
            # keep the minimal prefix reaching top_p mass: a position
            # survives when the mass BEFORE it is still < top_p
            keep = (cum - probs) < top_p_val.astype(jnp.float32)
            cutoff = jnp.min(jnp.where(keep, vals, jnp.inf), axis=-1,
                             keepdims=True)
            logits = jnp.where(logits < cutoff, -jnp.inf, logits)
        return logits
    if use_top_p:
        sorted_desc = -jnp.sort(-logits, axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the minimal prefix reaching top_p mass: a position survives
        # when the mass BEFORE it is still < top_p
        keep = (cum - probs) < top_p_val.astype(jnp.float32)
        cutoff = jnp.min(jnp.where(keep, sorted_desc, jnp.inf), axis=-1,
                         keepdims=True)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def _sample_logits_device(logits, key, temp_val, top_k, top_p_val, greedy,
                          use_top_p):
    """In-graph sampling head: greedy / temperature / top-k / top-p, all
    computed on device from the framework RNG (reference surface: paddlenlp
    generation's TopKProcess/TopPProcess, executed host-side there).
    ``greedy``/``top_k``/``use_top_p`` are STATIC (they shape the program);
    ``temp_val``/``top_p_val`` are traced scalars, so a serving loop varying
    them never recompiles."""
    if greedy:
        return jnp.argmax(logits.astype(jnp.float32),
                          axis=-1).astype(jnp.int32)
    filtered = _filter_logits(logits, temp_val, top_k, top_p_val, use_top_p)
    return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig, layer_idx=0):
        super().__init__()
        c = config
        #: position in the decoder stack — the batched multi-LoRA
        #: context (models/lora.py) gathers this layer's slice of the
        #: stacked adapter factors by it
        self.layer_idx = int(layer_idx)
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.fused = bool(getattr(c, "fuse_attention_qkv", False))
        if self.fused:
            self.qkv_proj = Linear(
                c.hidden_size,
                (self.num_heads + 2 * self.num_kv_heads) * self.head_dim,
                bias_attr=False)
        else:
            self.q_proj = Linear(c.hidden_size, self.num_heads * self.head_dim,
                                 bias_attr=False)
            self.k_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
            self.v_proj = Linear(c.hidden_size,
                                 self.num_kv_heads * self.head_dim,
                                 bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, c.hidden_size,
                             bias_attr=False)
        self.config = c

    def forward(self, x, rope_cache, attn_mask=None, kv_cache=None, position_offset=0):
        b, s = x.shape[0], x.shape[1]
        lora = active_lora()
        if self.fused:
            if lora is not None:
                raise ValueError(
                    "batched multi-LoRA targets the separate q/k/v "
                    "projections; fuse_attention_qkv is incompatible "
                    "with an armed adapter scope")
            qkv = self.qkv_proj(x)
            nq = self.num_heads * self.head_dim
            nkv = self.num_kv_heads * self.head_dim
            q = ops.reshape(qkv[:, :, :nq],
                            [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(qkv[:, :, nq:nq + nkv],
                            [b, s, self.num_kv_heads, self.head_dim])
            v = ops.reshape(qkv[:, :, nq + nkv:],
                            [b, s, self.num_kv_heads, self.head_dim])
        else:
            qf, kf, vf = self.q_proj(x), self.k_proj(x), self.v_proj(x)
            if lora is not None:
                # gathered per-slot adapter delta on top of each base
                # projection — slot 0 rows gather zeros (base tenant)
                qf = lora.apply("q_proj", self.layer_idx, x, qf)
                kf = lora.apply("k_proj", self.layer_idx, x, kf)
                vf = lora.apply("v_proj", self.layer_idx, x, vf)
            q = ops.reshape(qf, [b, s, self.num_heads, self.head_dim])
            k = ops.reshape(kf, [b, s, self.num_kv_heads, self.head_dim])
            v = ops.reshape(vf, [b, s, self.num_kv_heads, self.head_dim])

        def o_proj(t):
            out = self.o_proj(t)
            if lora is not None:
                out = lora.apply("o_proj", self.layer_idx, t, out)
            return out
        cos, sin = rope_cache
        if isinstance(position_offset, Tensor):
            # traced offset (static-shape decode): the offset is a dispatch
            # ARGUMENT, so every step shares one compiled entry
            q, k = dispatch(
                lambda qq, kk, off: apply_rope(qq, kk, cos, sin,
                                               off.astype(jnp.int32)),
                (q, k, position_offset), {}, name="rope_offset")
        else:
            q, k = dispatch(
                lambda qq, kk: apply_rope(qq, kk, cos, sin, position_offset),
                (q, k), {}, name="rope")
        if isinstance(kv_cache, PagedKVCache):
            # paged decode step (one new token/sequence) through the
            # block_multihead_attention op — the framework's own paged-KV
            # kernel as the generate() cache backend. GQA-capable: q keeps
            # num_heads, K/V the (possibly smaller) num_kv_heads.
            from ..incubate.nn import functional as IF
            H, Hkv, D = self.num_heads, self.num_kv_heads, self.head_dim
            kvq = kv_cache.quant
            qargs = dict(cache_k_quant_scales=kv_cache.k_scale,
                         cache_v_quant_scales=kv_cache.v_scale,
                         cache_quant_type=kvq) if kvq else {}
            if s != 1:
                # fused mixed step: S rows per slot, q_lens of them real —
                # the APPEND form of the op (Pallas append kernel on TPU,
                # dense scatter+gather fallback on CPU)
                if kv_cache.q_lens is None:
                    raise ValueError(
                        "PagedKVCache with seq len > 1 is the fused "
                        "append step and needs per-slot q_lens")
                qkv = ops.concat([ops.reshape(q, [b, s, H * D]),
                                  ops.reshape(k, [b, s, Hkv * D]),
                                  ops.reshape(v, [b, s, Hkv * D])], axis=-1)
                outs = IF.block_multihead_attention(
                    qkv, kv_cache.k, kv_cache.v, None, kv_cache.seq_lens,
                    kv_cache.q_lens, block_tables=kv_cache.block_tables,
                    **qargs)
                out, kc, vc = outs[:3]
                ks, vs = outs[3:] if kvq else (None, None)
                out = o_proj(ops.reshape(out, [b, s, H * D]))
                return out, PagedKVCache(
                    kc, vc, kv_cache.block_tables,
                    kv_cache.seq_lens + kv_cache.q_lens, kv_cache.q_lens,
                    k_scale=ks, v_scale=vs, quant=kvq)
            qkv = ops.concat([ops.reshape(q, [b, H * D]),
                              ops.reshape(k, [b, Hkv * D]),
                              ops.reshape(v, [b, Hkv * D])], axis=-1)
            outs = IF.block_multihead_attention(
                qkv, kv_cache.k, kv_cache.v, None, kv_cache.seq_lens, None,
                block_tables=kv_cache.block_tables, **qargs)
            out, kc, vc = outs[:3]
            ks, vs = outs[3:] if kvq else (None, None)
            out = o_proj(ops.reshape(out, [b, 1, H * D]))
            new_lens = kv_cache.seq_lens + 1
            return out, PagedKVCache(kc, vc, kv_cache.block_tables,
                                     new_lens, k_scale=ks, v_scale=vs,
                                     quant=kvq)
        if isinstance(kv_cache, ChunkKVCache):
            # fused mixed step, dense cache: write slot b's q_lens[b] real
            # rows at positions lens[b]+i via a DROP scatter (padding and
            # past-capacity rows vanish instead of dynamic-slice clamping
            # back over live history), causal mask against each row's own
            # absolute position — one compiled program serves any mix of
            # prefill chunks and decode tokens across slots.
            def chunk_write(kb, vb, kk, vv, lens, qlens):
                cap_t = kb.shape[1]
                lens = lens.astype(jnp.int32)
                i_idx = jnp.arange(s, dtype=jnp.int32)
                pos = lens[:, None] + i_idx[None, :]
                pos = jnp.where(i_idx[None, :] < qlens.astype(jnp.int32)
                                [:, None], pos, cap_t)      # OOB -> drop

                def upd(buf, new, p):
                    return buf.at[p].set(new.astype(buf.dtype),
                                         mode="drop")

                return (jax.vmap(upd)(kb, kk, pos),
                        jax.vmap(upd)(vb, vv, pos))

            k_buf, v_buf = dispatch(
                chunk_write,
                (kv_cache.k, kv_cache.v, k, v, kv_cache.lens,
                 kv_cache.q_lens), {}, name="chunk_kv_update")
            T = k_buf.shape[1]
            mask = dispatch(_window_causal_mask(s, T), (kv_cache.lens,),
                            {}, name="chunk_decode_mask")
            out = F.scaled_dot_product_attention(
                q, k_buf, v_buf, attn_mask=mask, is_causal=False,
                training=self.training)
            out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
            return o_proj(out), ChunkKVCache(
                k_buf, v_buf, kv_cache.lens, kv_cache.q_lens)
        if isinstance(kv_cache, SlotKVCache):
            # continuous-batching decode window (s=1 plain step, s=K a
            # speculative verify window): write slot b's s new positions at
            # its own length, rope at its own positions, causal mask against
            # its own prefix — one compiled program for ragged slots.
            def slot_step(kb, vb, kk, vv, lens):
                lens = lens.astype(jnp.int32)
                upd1 = jax.vmap(lambda buf, new, o:
                                jax.lax.dynamic_update_slice_in_dim(
                                    buf, new.astype(buf.dtype), o, 0))
                return upd1(kb, kk, lens), upd1(vb, vv, lens)

            k_buf, v_buf = dispatch(
                slot_step, (kv_cache.k, kv_cache.v, k, v, kv_cache.lens), {},
                name="slot_kv_update")
            T = k_buf.shape[1]
            mask = dispatch(_window_causal_mask(s, T), (kv_cache.lens,),
                            {}, name="slot_decode_mask")
            out = F.scaled_dot_product_attention(
                q, k_buf, v_buf, attn_mask=mask, is_causal=False,
                training=self.training)
            out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
            return o_proj(out), SlotKVCache(k_buf, v_buf, kv_cache.lens)
        if isinstance(kv_cache, StaticKVCache):
            def upd(buf, new, off):
                return jax.lax.dynamic_update_slice_in_dim(
                    buf, new.astype(buf.dtype), off.astype(jnp.int32), 1)

            k_buf = dispatch(upd, (kv_cache.k, k, position_offset), {},
                             name="kv_update")
            v_buf = dispatch(upd, (kv_cache.v, v, position_offset), {},
                             name="kv_update")
            T = k_buf.shape[1]

            def make_mask(off):
                # causal against the absolute position: query row q may see
                # cached/current positions <= off+q (for s=1 decode this is
                # the old "<= off" mask; for s>1 chunked prefill it keeps
                # causality WITHIN the chunk)
                rows = off.astype(jnp.int32) + jnp.arange(s, dtype=jnp.int32)
                valid = jnp.arange(T, dtype=jnp.int32)[None, None, None, :] \
                    <= rows[None, None, :, None]
                return jnp.where(valid, jnp.float32(0), jnp.float32(-1e30))

            mask = dispatch(make_mask, (position_offset,), {},
                            name="kv_decode_mask")
            out = F.scaled_dot_product_attention(
                q, k_buf, v_buf, attn_mask=mask, is_causal=False,
                training=self.training)
            out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
            return o_proj(out), StaticKVCache(k_buf, v_buf)
        if kv_cache is not None:
            k = ops.concat([kv_cache[0], k], axis=1)
            v = ops.concat([kv_cache[1], v], axis=1)
            kv_cache = (k, v)
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=(attn_mask is None),
            training=self.training)
        out = ops.reshape(out, [b, s, self.num_heads * self.head_dim])
        out = o_proj(out)
        return (out, kv_cache) if kv_cache is not None else out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig, layer_idx=0):
        super().__init__()
        c = config
        self.layer_idx = int(layer_idx)
        self.fused = bool(getattr(c, "fuse_swiglu", False))
        if self.fused:
            self.gate_up_proj = Linear(c.hidden_size, 2 * c.intermediate_size,
                                       bias_attr=False)
        else:
            self.gate_proj = Linear(c.hidden_size, c.intermediate_size,
                                    bias_attr=False)
            self.up_proj = Linear(c.hidden_size, c.intermediate_size,
                                  bias_attr=False)
        self.down_proj = Linear(c.intermediate_size, c.hidden_size, bias_attr=False)
        self._ff = c.intermediate_size

    def forward(self, x):
        lora = active_lora()
        if self.fused:
            if lora is not None:
                raise ValueError(
                    "batched multi-LoRA targets the separate gate/up "
                    "projections; fuse_swiglu is incompatible with an "
                    "armed adapter scope")
            gu = self.gate_up_proj(x)
            return self.down_proj(F.swiglu(gu[:, :, :self._ff],
                                           gu[:, :, self._ff:]))
        gate, up = self.gate_proj(x), self.up_proj(x)
        if lora is not None:
            gate = lora.apply("gate_proj", self.layer_idx, x, gate)
            up = lora.apply("up_proj", self.layer_idx, x, up)
        h = F.swiglu(gate, up)
        out = self.down_proj(h)
        if lora is not None:
            out = lora.apply("down_proj", self.layer_idx, h, out)
        return out


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig, layer_idx=0):
        super().__init__()
        self.self_attn = LlamaAttention(config, layer_idx=layer_idx)
        self.mlp = LlamaMLP(config, layer_idx=layer_idx)
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, x, rope_cache, attn_mask=None, kv_cache=None,
                position_offset=0):
        if kv_cache is not None:
            attn_out, new_cache = self.self_attn(
                self.input_layernorm(x), rope_cache, attn_mask, kv_cache,
                position_offset)
            x = x + attn_out
            x = x + self.mlp(self.post_attention_layernorm(x))
            return x, new_cache
        x = x + self.self_attn(self.input_layernorm(x), rope_cache, attn_mask)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size)
        self.layers = LayerList([LlamaDecoderLayer(config, layer_idx=i)
                                 for i in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        cos, sin = precompute_rope(head_dim, config.max_position_embeddings,
                                   config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, attn_mask=None, kv_caches=None,
                position_offset=0):
        x = self.embed_tokens(input_ids)
        rope = (self.rope_cos._value, self.rope_sin._value)
        if kv_caches is not None:
            new_caches = []
            for layer, cache in zip(self.layers, kv_caches):
                x, c = layer(x, rope, attn_mask, cache, position_offset)
                new_caches.append(c)
            return self.norm(x), new_caches
        remat = self.config.use_recompute and self.training
        if remat:
            from ..distributed.fleet.recompute import recompute
        for layer in self.layers:
            if remat:
                x = recompute(layer, x, rope, attn_mask,
                              checkpoint_policy=self.config.recompute_policy)
            else:
                x = layer(x, rope, attn_mask)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if not config.tie_word_embeddings:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  bias_attr=False)

    def _logits(self, hidden):
        if self.config.tie_word_embeddings:
            return ops.matmul(hidden, self.llama.embed_tokens.weight,
                              transpose_y=True)
        return self.lm_head(hidden)

    def forward(self, input_ids, labels=None, attn_mask=None):
        hidden = self.llama(input_ids, attn_mask)
        logits = self._logits(hidden)
        if labels is None:
            return logits
        loss = F.cross_entropy(
            # no fp32 pre-cast: cross_entropy's fused path accumulates
            # the lse in fp32 internally without copying the logits
            ops.reshape(logits, [-1, self.config.vocab_size]),
            ops.reshape(labels, [-1]), ignore_index=-100)
        return loss, logits

    def _gen_programs(self, B, prompt_len, limit, total, temperature, top_k,
                      top_p, eos_token_id, cache_impl, block_size):
        """Build (or fetch cached) the two compiled generation programs:

        - ``prefill``: embed -> all layers (causal flash) -> last-position
          logits + per-layer KV buffers, as ONE jitted program.
        - ``decode``: the ENTIRE decode loop as one jitted program — a
          ``lax.while_loop`` whose body is sample (on-device, from the
          framework RNG) -> one-token model step -> cache write. No logits
          ever travel to host; the only host transfer is the final token
          buffer. With TP/dp-sharded weights the same programs partition
          under GSPMD (single-controller SPMD decode).

        Reference analog: the fused-decode serving stack —
        incubate/nn/functional/masked_multihead_attention.py:1 (dense) /
        block_multihead_attention.py:1 (paged) under AnalysisPredictor
        (paddle/fluid/inference/api/analysis_predictor.h:101)."""
        from ..jit.functional_call import collect_state, bind_state

        c = self.config
        # temperature/top_p VALUES are traced decode args; only the program
        # STRUCTURE (greedy vs sampling, top-k width, nucleus on/off) keys
        # the compile cache — varying sampling params never recompiles
        greedy = float(temperature) <= 0.0
        use_top_p = bool(top_p) and float(top_p) < 1.0
        key = (B, prompt_len, limit, total, greedy, int(top_k), use_top_p,
               eos_token_id, cache_impl, int(block_size))
        cache = getattr(self, "_gen_cache", None)
        if cache is None:
            cache = self._gen_cache = {}
        if key in cache:
            return cache[key]

        _, params, _, buffers = collect_state(self)
        state = params + buffers
        head_dim = c.hidden_size // c.num_attention_heads
        kvh = c.num_key_value_heads
        n_layers = c.num_hidden_layers
        paged = cache_impl == "paged"
        bs = int(block_size)
        mb = -(-total // bs)  # blocks per sequence

        dt = self.llama.embed_tokens.weight.dtype

        def prefill(state_vals, ids_v):
            empty = [(Tensor(jnp.zeros((B, 0, kvh, head_dim), dt)),
                      Tensor(jnp.zeros((B, 0, kvh, head_dim), dt)))
                     for _ in range(n_layers)]
            with functional_mode(), bind_state(state, state_vals):
                hidden, grown = self.llama(Tensor(ids_v), kv_caches=empty,
                                           position_offset=0)
                logits = self._logits(hidden[:, -1:])._value[:, 0]
            if paged:
                # scatter prompt KV into the block pools: logical block i of
                # sequence b lives at physical block b*mb + i
                k_bufs, v_bufs = [], []
                for k, v in grown:
                    def pool(t):
                        tv = t._value
                        pad = mb * bs - tv.shape[1]
                        tv = jnp.pad(tv, ((0, 0), (0, pad), (0, 0), (0, 0)))
                        tv = tv.reshape(B, mb, bs, kvh, head_dim)
                        return jnp.moveaxis(tv, 2, 3).reshape(
                            B * mb, kvh, bs, head_dim)
                    k_bufs.append(pool(k))
                    v_bufs.append(pool(v))
            else:
                def to_static(t):
                    pad = total - t.shape[1]
                    return jnp.pad(t._value,
                                   ((0, 0), (0, pad), (0, 0), (0, 0)))
                k_bufs = [to_static(k) for k, _ in grown]
                v_bufs = [to_static(v) for _, v in grown]
            return logits, k_bufs, v_bufs

        tables = jnp.arange(B * mb, dtype=jnp.int32).reshape(B, mb)

        def decode(state_vals, k_bufs, v_bufs, logits0, rng_key, temp_val,
                   top_p_val):
            buf0 = jnp.zeros((B, limit), jnp.int32)
            finished0 = jnp.zeros((B,), bool)

            def cond(carry):
                i, _, _, _, _, finished, _ = carry
                cont = i < limit
                if eos_token_id is not None:
                    cont = jnp.logical_and(cont, ~jnp.all(finished))
                return cont

            def body(carry):
                i, logits, kb, vb, rkey, finished, buf = carry
                rkey, sub = jax.random.split(rkey)
                nxt = _sample_logits_device(logits, sub, temp_val,
                                            int(top_k), top_p_val, greedy,
                                            use_top_p)
                if eos_token_id is not None:
                    nxt = jnp.where(finished, jnp.int32(eos_token_id), nxt)
                    finished = finished | (nxt == eos_token_id)
                buf = jax.lax.dynamic_update_slice(buf, nxt[:, None],
                                                   (jnp.int32(0), i))
                off = jnp.int32(prompt_len) + i
                with functional_mode(), bind_state(state, state_vals):
                    if paged:
                        lens = jnp.full((B,), off, jnp.int32)
                        caches = [PagedKVCache(k, v, tables, lens)
                                  for k, v in zip(kb, vb)]
                    else:
                        caches = [StaticKVCache(k, v)
                                  for k, v in zip(kb, vb)]
                    last_h, new_caches = self.llama(
                        Tensor(nxt[:, None]), kv_caches=caches,
                        position_offset=Tensor(off))
                    logits = self._logits(last_h)._value[:, 0]
                kb = [getattr(cc.k, "_value", cc.k) for cc in new_caches]
                vb = [getattr(cc.v, "_value", cc.v) for cc in new_caches]
                return (i + 1, logits, kb, vb, rkey, finished, buf)

            i, _, _, _, _, _, buf = jax.lax.while_loop(
                cond, body,
                (jnp.int32(0), logits0, k_bufs, v_bufs, rng_key, finished0,
                 buf0))
            return buf, i

        # decode consumes the prefill-built caches exactly once — donate them
        # so the cache update is in-place (no 2x KV footprint on chip)
        entry = (jax.jit(prefill), jax.jit(decode, donate_argnums=(1, 2)))
        cache[key] = entry
        return entry

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, top_p=1.0, eos_token_id=None, cache_impl="static",
                 block_size=64):
        """Autoregressive decoding, fully compiled (reference surface:
        paddlenlp GenerationMixin.generate over the fused-decode inference
        stack; the reference keeps it out-of-tree, the flagship model here
        ships it in-core).

        Prefill is ONE compiled program (causal flash over the prompt);
        the whole decode loop is ONE more (on-device while_loop: sample ->
        one-token step -> cache write), so logits never round-trip to host
        and per-token cost is pure device compute. ``cache_impl="static"``
        holds dense fixed-capacity per-layer buffers (:class:`StaticKVCache`)
        written at a traced offset; ``cache_impl="paged"`` routes decode
        attention through the framework's
        ``block_multihead_attention`` paged-KV op (:class:`PagedKVCache`,
        ``block_size``-token blocks). temperature<=0 = greedy; top_k/top_p
        sampling draws from the framework RNG (``paddle.seed``-
        deterministic). Works with TP/dp-sharded weights on a mesh (the
        programs partition under GSPMD). Decoding is capped at
        ``max_position_embeddings`` (the rope table's end) with a warning.
        """
        from ..core import random as _random

        c = self.config
        ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(jnp.asarray(np.asarray(input_ids), jnp.int32))
        B, prompt_len = ids.shape[0], ids.shape[1]
        if prompt_len >= c.max_position_embeddings:
            raise ValueError(
                f"prompt length {prompt_len} >= max_position_embeddings "
                f"{c.max_position_embeddings}: no positions left to decode")
        if cache_impl not in ("static", "paged"):
            raise ValueError(f"unknown cache_impl {cache_impl!r}")
        limit = min(int(max_new_tokens),
                    c.max_position_embeddings - prompt_len)
        if limit < int(max_new_tokens):
            import warnings
            warnings.warn(
                f"generate: capping max_new_tokens {max_new_tokens} -> "
                f"{limit} (rope table ends at position "
                f"{c.max_position_embeddings})", RuntimeWarning,
                stacklevel=2)
        if limit <= 0:
            return Tensor(jnp.zeros((B, 0), jnp.int64))
        total = prompt_len + limit
        seed, counter = _random.default_generator.next_seed()
        rng_key = jax.random.fold_in(jax.random.PRNGKey(seed), counter)

        was_training = self.training
        self.eval()
        try:
            prefill, decode = self._gen_programs(
                B, prompt_len, limit, total, temperature, top_k, top_p,
                eos_token_id, cache_impl, block_size)
            from ..jit.functional_call import collect_state, read_values
            _, params, _, buffers = collect_state(self)
            state_vals = read_values(params + buffers)
            logits0, k_bufs, v_bufs = prefill(state_vals,
                                              ids._value.astype(jnp.int32))
            buf, n = decode(state_vals, k_bufs, v_bufs, logits0, rng_key,
                            jnp.float32(max(float(temperature), 1e-6)),
                            jnp.float32(top_p))
        finally:
            if was_training:
                self.train()
        n = int(np.asarray(n))
        out = np.asarray(buf)[:, :n]
        return Tensor(jnp.asarray(out, jnp.int64))

    def flops_per_token(self, seq_len):
        """Model FLOPs per token (fwd+bwd 3x fwd) for MFU accounting."""
        c = self.config
        d, L = c.hidden_size, c.num_hidden_layers
        ff = c.intermediate_size
        per_layer = (
            2 * d * d * (1 + 2 * c.num_key_value_heads / c.num_attention_heads + 1)
            + 2 * 2 * d * seq_len / 2  # attention scores+values (causal half)
            + 2 * 3 * d * ff
        )
        embed = 2 * d * c.vocab_size
        return 3 * (L * per_layer + embed)
