"""Shared model-head helpers.

Currently: the guard object the fused-head paths return in place of
logits (see :class:`FusedLogitsUnavailable`).
"""
from __future__ import annotations

__all__ = ["FusedLogitsUnavailable"]


class FusedLogitsUnavailable:
    """Placeholder returned as ``logits`` by the fused head+CE paths
    (``BertConfig.fuse_mlm_head_ce`` / ``GPTConfig.fuse_lm_head_ce``).

    The whole point of the fused path is to NEVER materialize the
    [tokens, vocab] logits tensor, so the model returns ``(loss,
    FusedLogitsUnavailable(...))`` where the unfused path returns
    ``(loss, logits)``. The object is falsy (so ``if logits:`` guards
    behave like the old ``None``), but ANY real consumption — attribute
    access, indexing, iteration, numpy conversion — raises a RuntimeError
    naming the flag to turn off, instead of the bare
    ``'NoneType' object has no attribute ...`` the old contract produced.
    """

    __slots__ = ("_flag",)

    def __init__(self, flag):
        object.__setattr__(self, "_flag", flag)

    def _raise(self, *a, **k):
        flag = object.__getattribute__(self, "_flag")
        raise RuntimeError(
            f"logits are not materialized under {flag}=True — the fused "
            f"head computes the loss without the [tokens, vocab] logits "
            f"tensor. Disable {flag} (or call the model without labels) "
            f"to get logits.")

    def __bool__(self):
        return False

    def __repr__(self):
        return (f"<FusedLogitsUnavailable "
                f"{object.__getattribute__(self, '_flag')}=True>")

    def __getattr__(self, name):
        # dunder probes (copy/pickle/inspection machinery) get the normal
        # AttributeError; real consumption (.numpy(), ._value, .shape, …)
        # gets the explanatory RuntimeError
        if name.startswith("__") and name.endswith("__"):
            raise AttributeError(name)
        self._raise()

    # every other consumption path raises the explanatory error
    __getitem__ = _raise
    __iter__ = _raise
    __len__ = _raise
    __array__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = _raise
    __mul__ = __rmul__ = __matmul__ = __rmatmul__ = _raise
