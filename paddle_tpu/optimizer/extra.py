"""The long-tail optimizer family: ASGD, Rprop, RAdam, NAdam.

Reference semantics: python/paddle/optimizer/{asgd,rprop,radam,nadam}.py with the
authoritative update rules in phi kernels (paddle/phi/kernels/cpu/asgd_kernel.cc,
rprop_kernel.cc, impl/nadam_kernel_impl.h, impl/radam_kernel_impl.h). Each is a
pure `_apply` rule on the shared Optimizer base, so they fuse into the jitted
multi-tensor update like the rest of the family.
"""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer


class ASGD(Optimizer):
    """Stochastic Average Gradient (reference asgd.py — SAG, Schmidt et al.).

    Keeps a running sum ``d`` of the most recent gradient per batch slot
    (``ys[i]``, i = step % batch_num) so the update uses the average of the
    last ``batch_num`` gradients: ``p -= lr * d / min(step+1, n)``.
    """

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if batch_num is None or batch_num <= 0:
            raise ValueError("batch_num should be greater than 0")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._n = int(batch_num)

    def _init_slots(self, v):
        return {"d": jnp.zeros_like(v),
                "ys": jnp.zeros((self._n,) + v.shape, v.dtype),
                "m": jnp.zeros((), jnp.int64)}

    def _apply(self, p, g, slots, lr, step):
        m = slots["m"]
        idx = (m % self._n).astype(jnp.int32)
        y = slots["ys"][idx]
        d = slots["d"] - y + g
        ys = slots["ys"].at[idx].set(g)
        n_eff = jnp.minimum(m + 1, self._n).astype(p.dtype)
        new_p = p - lr.astype(p.dtype) * d / n_eff
        return new_p, {"d": d, "ys": ys, "m": m + 1}


class Rprop(Optimizer):
    """Resilient backprop (reference rprop.py; kernel rprop_kernel.cc).

    Per-element learning rates adapted by the sign of grad*prev_grad:
    agree -> lr*eta+, disagree -> lr*eta- and the step is skipped (grad zeroed),
    then ``p -= sign(grad) * lr`` with lr clipped to learning_rate_range.
    """

    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=False, name=None):
        if not (0.0 < learning_rate_range[0] <= learning_rate
                <= learning_rate_range[1]):
            raise ValueError(
                "'0.0 < learning_rate_range[0] <= learning_rate <= "
                "learning_rate_range[1]' must be true")
        if not 0.0 < etas[0] < 1.0 < etas[1]:
            raise ValueError("'0.0 < etas[0] < 1.0 < etas[1]' must be true")
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = float(learning_rate_range[0]), \
            float(learning_rate_range[1])
        self._eta_neg, self._eta_pos = float(etas[0]), float(etas[1])
        self._lr0 = float(learning_rate)

    def _init_slots(self, v):
        return {"prevs": jnp.zeros_like(v),
                "learning_rates": jnp.full_like(v, self._lr0)}

    def _apply(self, p, g, slots, lr, step):
        prod = g * slots["prevs"]
        eta = jnp.where(prod > 0, self._eta_pos,
                        jnp.where(prod < 0, self._eta_neg, 1.0)).astype(p.dtype)
        g = jnp.where(prod < 0, jnp.zeros_like(g), g)
        lrs = jnp.clip(slots["learning_rates"] * eta, self._lr_min, self._lr_max)
        new_p = p - jnp.sign(g) * lrs
        return new_p, {"prevs": g, "learning_rates": lrs}


class RAdam(Optimizer):
    """Rectified Adam (reference radam.py / radam_kernel_impl.h)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_slots(self, v):
        return {"moment1": jnp.zeros_like(v), "moment2": jnp.zeros_like(v)}

    def _apply(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        t = step.astype(jnp.float32)
        b1t = jnp.power(b1, t)
        b2t = jnp.power(b2, t)
        m_hat = m / (1 - b1t)
        rho_inf = 2.0 / (1 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * b2t / (1 - b2t)
        # rectification term (defined where rho_t > 4; guarded for the tracer)
        safe_rho = jnp.maximum(rho_t, 4.0 + 1e-3)
        r = jnp.sqrt(((safe_rho - 4) * (safe_rho - 2) * rho_inf)
                     / ((rho_inf - 4) * (rho_inf - 2) * safe_rho))
        adaptive = r * m_hat * jnp.sqrt(1 - b2t) / (jnp.sqrt(v) + self._eps)
        sgd_like = m_hat
        update = jnp.where(rho_t > 5.0, adaptive, sgd_like).astype(p.dtype)
        return p - lr.astype(p.dtype) * update, {"moment1": m, "moment2": v}


class NAdam(Optimizer):
    """Nesterov-momentum Adam (reference nadam.py / nadam_kernel_impl.h)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if momentum_decay < 0:
            raise ValueError(
                f"Invalid momentum_decay value: {momentum_decay}, expect "
                "momentum_decay >= 0.")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._psi = momentum_decay

    def _init_slots(self, v):
        return {"moment1": jnp.zeros_like(v), "moment2": jnp.zeros_like(v),
                "momentum_decay_pow": jnp.ones((), jnp.float32),
                "beta2_pow": jnp.ones((), jnp.float32),
                "mu_product": jnp.ones((), jnp.float32)}

    def _apply(self, p, g, slots, lr, step):
        b1, b2, psi = self._beta1, self._beta2, self._psi
        mdp = slots["momentum_decay_pow"] * 0.96
        b2p = slots["beta2_pow"] * b2
        mu_t = b1 * (1 - 0.5 * jnp.power(mdp, psi))
        mu_t1 = b1 * (1 - 0.5 * jnp.power(mdp, psi) * (0.96 ** psi))
        mu_prod = slots["mu_product"] * mu_t
        mu_prod_t1 = mu_prod * mu_t1
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        m_hat = mu_t1 * m / (1 - mu_prod_t1) + (1 - mu_t) * g / (1 - mu_prod)
        v_hat = v / (1 - b2p)
        new_p = p - lr.astype(p.dtype) * m_hat / (jnp.sqrt(v_hat) + self._eps)
        return new_p, {"moment1": m, "moment2": v, "momentum_decay_pow": mdp,
                       "beta2_pow": b2p, "mu_product": mu_prod}
