"""Optimizer base + the standard family.

Reference: python/paddle/optimizer/{optimizer,adam,adamw,momentum,...}.py → phi fused
adam/momentum kernels. TPU-native design: each optimizer is a *pure update rule*
(`_apply`: (param, grad, slots, lr, step) -> (new_param, new_slots)); the whole
parameter set updates in ONE jitted, buffer-donated call (the analog of the
reference's multi_tensor fused_adam path), and the same pure rule is reused by the
jit train-step, ZeRO sharding, and the distributed shard_optimizer.

Master weights: like the reference's multi_precision mode, bf16/fp16 params keep an
fp32 master copy in the slot dict; updates happen in fp32 and cast down.
"""
from __future__ import annotations

import functools
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, no_grad
from ..nn.layer_base import Parameter
from .clip import ClipGradBase, ClipGradByGlobalNorm
from .lr import LRScheduler


def _is_low_precision(dtype):
    return dtype in (jnp.bfloat16, jnp.float16)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters or []
        self._learning_rate = learning_rate
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        # paddle: float weight_decay == L2Decay coupled regularization;
        # regularizer objects carry _kind ("l1"/"l2", regularizer/__init__.py)
        self._wd_kind = "l2"
        if weight_decay is None:
            self._wd = 0.0
            self._decoupled_wd = False
        elif isinstance(weight_decay, (int, float)):
            self._wd = float(weight_decay)
            self._decoupled_wd = False
        else:  # L1Decay/L2Decay object
            self._wd = float(getattr(weight_decay, "_coeff", 0.0))
            self._wd_kind = getattr(weight_decay, "_kind", "l2")
            self._decoupled_wd = False
        self._slots: dict[int, dict] = {}
        self._step_count = 0
        self._jit_update = None
        self._jit_shape_key = None

    # -- subclass interface ---------------------------------------------------
    def _init_slots(self, p_val) -> dict:
        return {}

    def _apply(self, p, g, slots, lr, step) -> tuple:
        raise NotImplementedError

    def _decay_mask(self, param) -> bool:
        """Whether decoupled weight decay applies to this param (AdamW hook)."""
        return True

    # -- lr -------------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate.get_lr())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    # -- pure tree update (shared by eager + jit paths) -----------------------
    def apply_updates(self, vals, grads, slots, lr, step, decay_flags,
                      fused_ctx=None):
        """Pure: lists of arrays -> (new_vals, new_slots). Used under jit.

        ``fused_ctx`` (optional, aligned with vals): per-param context for the
        fused kernel — None for the default whole-array path, or
        ``(mesh, spec)`` to run it shard_map-wise on sharded state (set by the
        ZeRO wrapper; replaces any process-global flag toggling)."""
        if self._grad_clip is not None:
            grads = self._grad_clip.apply(vals, grads)
        fused = getattr(self, "_apply_fused", None)
        fused_takes_pid = self.__dict__.get("_fused_takes_param_id")
        if fused is not None and fused_takes_pid is None:
            import inspect
            try:
                fused_takes_pid = "param_id" in inspect.signature(
                    fused).parameters
            except (TypeError, ValueError):
                fused_takes_pid = False
            self._fused_takes_param_id = fused_takes_pid
        new_vals, new_slots = [], []
        for i, (p, g, s, dm) in enumerate(zip(vals, grads, slots, decay_flags)):
            if g is None:
                new_vals.append(p)
                new_slots.append(s)
                continue
            if fused is not None:
                ctx = fused_ctx[i] if fused_ctx is not None else None
                kw = {"param_id": i} if fused_takes_pid else {}
                out = fused(p, g, s, lr, step, dm, shard_ctx=ctx, **kw)
                if out is not None:
                    new_vals.append(out[0])
                    new_slots.append(out[1])
                    continue
            master = s.get("master_weight")
            work_p = master if master is not None else p
            g32 = g.astype(work_p.dtype)
            if self._wd and not self._decoupled_wd:
                if self._wd_kind == "l1":
                    g32 = g32 + self._wd * jnp.sign(work_p)
                else:
                    g32 = g32 + self._wd * work_p
            np_, ns = self._apply(work_p, g32, s, lr, step)
            if self._decoupled_wd and self._wd and dm:
                np_ = np_ - lr * self._wd * work_p
            if master is not None:
                ns = dict(ns)
                ns["master_weight"] = np_
                new_vals.append(np_.astype(p.dtype))
            else:
                new_vals.append(np_)
            new_slots.append(ns)
        return new_vals, new_slots

    # -- eager step -----------------------------------------------------------
    def _ensure_slots(self, params):
        for p in params:
            if id(p) not in self._slots:
                v = p._value

                def build(v):
                    s = self._init_slots(
                        v.astype(jnp.float32)
                        if (self._multi_precision and
                            _is_low_precision(v.dtype)) else v)
                    if self._multi_precision and _is_low_precision(v.dtype):
                        s["master_weight"] = v.astype(jnp.float32)
                    return s

                if isinstance(v, jax.ShapeDtypeStruct):
                    # LazyGuard-abstract param: slots stay abstract too (the
                    # same _init_slots logic, evaluated shape-only) — enables
                    # AOT compile/memory planning of the full train step
                    # without materializing optimizer state. eval_shape drops
                    # shardings, so param-shaped slots re-attach the param's
                    # (matching eager, where zeros_like(v) inherits it)
                    slots = jax.eval_shape(build, v)
                    sh = getattr(v, "sharding", None)
                    if sh is not None:
                        slots = {
                            k: (jax.ShapeDtypeStruct(s.shape, s.dtype,
                                                     sharding=sh)
                                if tuple(s.shape) == tuple(v.shape) else s)
                            for k, s in slots.items()}
                    self._slots[id(p)] = slots
                else:
                    self._slots[id(p)] = build(v)

    @no_grad()
    def step(self):
        params = [p for p in self._parameter_list
                  if not p.stop_gradient and p.grad is not None]
        if not params:
            self._step_count += 1
            if isinstance(self._learning_rate, LRScheduler):
                pass
            return
        self._ensure_slots(params)
        vals = [p._value for p in params]
        grads = [p.grad._value for p in params]
        slots = [self._slots[id(p)] for p in params]
        decay_flags = tuple(bool(self._decay_mask(p)) for p in params)
        self._step_count += 1
        lr = jnp.asarray(self.get_lr(), jnp.float32)
        step = jnp.asarray(self._step_count, jnp.int32)

        from ..core.flags import flag_value
        # the fused-update flag is read at trace time — key the jit cache on
        # it so set_flags toggles take effect on the next step
        shape_key = tuple((v.shape, str(v.dtype)) for v in vals) + \
            (decay_flags, bool(flag_value("use_fused_adamw")),
             bool(flag_value("adamw_stochastic_rounding")))
        if self._jit_update is None or self._jit_shape_key != shape_key:
            fn = functools.partial(self._traced_update, decay_flags=decay_flags)
            self._jit_update = jax.jit(fn, donate_argnums=(0, 2))
            self._jit_shape_key = shape_key
        new_vals, new_slots = self._jit_update(vals, grads, slots, lr, step)
        for p, nv, ns in zip(params, new_vals, new_slots):
            p._value = nv
            self._slots[id(p)] = ns

    def _traced_update(self, vals, grads, slots, lr, step, decay_flags):
        return self.apply_updates(vals, grads, slots, lr, step, decay_flags)

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    # -- state dict -----------------------------------------------------------
    def state_dict(self):
        out = {"_step_count": self._step_count}
        name_map = self._param_names()
        for p in self._parameter_list:
            if id(p) in self._slots:
                pname = name_map[id(p)]
                for k, v in self._slots[id(p)].items():
                    out[f"{pname}.{k}"] = Tensor(v)
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        self._step_count = int(state.get("_step_count", 0))
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        names = self._param_names()
        for p in self._parameter_list:
            pname = names[id(p)]
            slot = {}
            for key, value in state.items():
                if isinstance(key, str) and key.startswith(pname + "."):
                    slot_name = key[len(pname) + 1:]
                    slot[slot_name] = value._value if isinstance(value, Tensor) \
                        else jnp.asarray(value)
            if slot:
                self._slots[id(p)] = slot

    def _param_names(self):
        return {id(p): (p.name or f"param_{i}")
                for i, p in enumerate(self._parameter_list)}


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _apply(self, p, g, slots, lr, step):
        return p - lr.astype(p.dtype) * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, v):
        return {"velocity": jnp.zeros_like(v)}

    def _apply(self, p, g, slots, lr, step):
        vel = self._momentum * slots["velocity"] + g
        if self._nesterov:
            update = g + self._momentum * vel
        else:
            update = vel
        return p - lr.astype(p.dtype) * update, {"velocity": vel}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=True, use_multi_tensor=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._amsgrad = amsgrad

    def _init_slots(self, v):
        from ..core.flags import flag_value
        mdt = jnp.bfloat16 if (flag_value("adamw_bf16_moments")
                               and v.dtype == jnp.float32) else v.dtype
        s = {"moment1": jnp.zeros(v.shape, mdt),
             "moment2": jnp.zeros(v.shape, mdt)}
        if self._amsgrad:
            s["moment2_max"] = jnp.zeros(v.shape, mdt)
        return s

    def _apply(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        mdt = slots["moment1"].dtype
        m1 = slots["moment1"].astype(p.dtype)  # fp32 math; bf16-storable
        m2 = slots["moment2"].astype(p.dtype)
        m = b1 * m1 + (1 - b1) * g
        v = b2 * m2 + (1 - b2) * jnp.square(g)
        stepf = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(b1, stepf)
        bc2 = 1 - jnp.power(b2, stepf)
        ns = {"moment1": m.astype(mdt), "moment2": v.astype(mdt)}
        if self._amsgrad:
            vmax = jnp.maximum(slots["moment2_max"].astype(p.dtype), v)
            ns["moment2_max"] = vmax.astype(mdt)
            denom = jnp.sqrt(vmax / bc2) + self._eps
        else:
            denom = jnp.sqrt(v / bc2) + self._eps
        update = (m / bc1) / denom
        return p - lr.astype(p.dtype) * update, ns

    def _apply_fused(self, p, g, slots, lr, step, decay_mask, shard_ctx=None,
                     param_id=0):
        """Single-pass Pallas update for the multi-precision path (the
        reference's fused_adam/multi_tensor analog). Covers plain Adam with
        no coupled decay and AdamW's decoupled decay; anything else falls
        back to the generic chain. With ``shard_ctx=(mesh, spec)`` the kernel
        runs shard_map-wise on each device's local shard (ZeRO state)."""
        if self._amsgrad or (self._wd and not self._decoupled_wd):
            return None
        from ..core.flags import flag_value
        if not flag_value("use_fused_adamw"):
            return None
        kw = dict(beta1=self._beta1, beta2=self._beta2, eps=self._eps,
                  weight_decay=self._wd if self._decoupled_wd else 0.0,
                  apply_decay=bool(decay_mask))
        if slots.get("master_weight") is None:
            # master-weight-free path: bf16 params integrate updates via
            # in-kernel STOCHASTIC ROUNDING (flag-gated — different
            # trajectories than the fp32-master reference chain)
            if not flag_value("adamw_stochastic_rounding"):
                return None
            if p.dtype != jnp.bfloat16:
                return None
            # per-(step, param) rounding seed, derived in-graph — folding the
            # param index in decorrelates the rounding streams of same-shaped
            # parameters (step-only seeding repeats the identical per-position
            # stream across every layer)
            seed_f = jax.lax.bitcast_convert_type(
                ((step.astype(jnp.int32) + jnp.int32(int(param_id) * 2654435761
                                                    & 0x7FFFFFFF))
                 * jnp.int32(-1640531527)
                 ^ jnp.int32(0x5BD1E995)).reshape(1, 1), jnp.float32)
            if shard_ctx is not None:
                # ZeRO/TP-sharded state: shard_map the SR kernel over the
                # local shards — falling back to the generic chain here
                # would DETERMINISTICALLY round bf16 params and silently
                # stall training on small updates
                from ..ops.kernels.fused_adamw import (
                    fused_adamw_sr_update_sharded)
                mesh, spec = shard_ctx
                out = fused_adamw_sr_update_sharded(
                    mesh, spec, p, g, slots["moment1"], slots["moment2"],
                    lr, step, seed_f, **kw)
            else:
                from ..ops.kernels.fused_adamw import fused_adamw_sr_update
                out = fused_adamw_sr_update(p, g, slots["moment1"],
                                            slots["moment2"], lr, step,
                                            seed_f, **kw)
            if out is None:
                import warnings
                warnings.warn(
                    "adamw_stochastic_rounding: shape not tileable for the "
                    "SR kernel — falling back to DETERMINISTIC bf16 "
                    "rounding for this parameter (small updates may stall)",
                    RuntimeWarning, stacklevel=2)
                return None
            new_p, nm, nv = out
            return new_p, {"moment1": nm, "moment2": nv}
        if slots["moment1"].dtype != jnp.float32:
            return None  # the master-weight Pallas kernel assumes fp32 moments
        if shard_ctx is not None:
            from ..ops.kernels.fused_adamw import fused_adamw_update_sharded
            mesh, spec = shard_ctx
            out = fused_adamw_update_sharded(
                mesh, spec, p, g, slots["moment1"], slots["moment2"],
                slots["master_weight"], lr, step, **kw)
        else:
            from ..ops.kernels.fused_adamw import fused_adamw_update
            out = fused_adamw_update(
                p, g, slots["moment1"], slots["moment2"],
                slots["master_weight"], lr, step, **kw)
        if out is None:  # untileable shape — generic path
            return None
        new_p, nm, nv, nmw = out
        return new_p, {"moment1": nm, "moment2": nv, "master_weight": nmw}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=True, amsgrad=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._wd = float(weight_decay) if not hasattr(weight_decay, "_coeff") \
            else float(weight_decay._coeff)
        self._decoupled_wd = True
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_mask(self, param):
        if self._apply_decay_param_fun is not None:
            return bool(self._apply_decay_param_fun(param.name or ""))
        return True


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, v):
        return {"moment": jnp.full_like(v, self._init_acc)}

    def _apply(self, p, g, slots, lr, step):
        acc = slots["moment"] + jnp.square(g)
        return p - lr.astype(p.dtype) * g / (jnp.sqrt(acc) + self._eps), {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho, self._eps = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _init_slots(self, v):
        s = {"mean_square": jnp.zeros_like(v), "velocity": jnp.zeros_like(v)}
        if self._centered:
            s["mean_grad"] = jnp.zeros_like(v)
        return s

    def _apply(self, p, g, slots, lr, step):
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * jnp.square(g)
        ns = {"mean_square": ms}
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            ns["mean_grad"] = mg
            denom = jnp.sqrt(ms - jnp.square(mg) + self._eps)
        else:
            denom = jnp.sqrt(ms + self._eps)
        vel = self._momentum * slots["velocity"] + lr.astype(p.dtype) * g / denom
        ns["velocity"] = vel
        return p - vel, ns


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho, self._eps = rho, epsilon

    def _init_slots(self, v):
        return {"avg_squared_grad": jnp.zeros_like(v),
                "avg_squared_update": jnp.zeros_like(v)}

    def _apply(self, p, g, slots, lr, step):
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * jnp.square(g)
        update = -jnp.sqrt(slots["avg_squared_update"] + self._eps) / \
            jnp.sqrt(asg + self._eps) * g
        asu = self._rho * slots["avg_squared_update"] + \
            (1 - self._rho) * jnp.square(update)
        return p + lr.astype(p.dtype) * update, \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon

    def _init_slots(self, v):
        return {"moment": jnp.zeros_like(v), "inf_norm": jnp.zeros_like(v)}

    def _apply(self, p, g, slots, lr, step):
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        stepf = step.astype(jnp.float32)
        bc1 = 1 - jnp.power(self._beta1, stepf)
        return p - lr.astype(p.dtype) / bc1 * m / (u + self._eps), \
            {"moment": m, "inf_norm": u}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._beta1, self._beta2, self._eps = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_slots(self, v):
        return {"moment1": jnp.zeros_like(v), "moment2": jnp.zeros_like(v)}

    def _apply(self, p, g, slots, lr, step):
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * jnp.square(g)
        stepf = step.astype(jnp.float32)
        mh = m / (1 - jnp.power(b1, stepf))
        vh = v / (1 - jnp.power(b2, stepf))
        r = mh / (jnp.sqrt(vh) + self._eps) + self._lamb_wd * p
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr.astype(p.dtype) * trust * r, {"moment1": m, "moment2": v}


# canonical definitions live in paddle_tpu.regularizer; re-exported here for the
# paddle.optimizer.L1Decay/L2Decay call sites
from ..regularizer import L1Decay, L2Decay  # noqa: E402,F401
