"""paddle.optimizer analog."""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adagrad, RMSProp, Adadelta, Adamax, Lamb,
    L2Decay, L1Decay,
)
from .extra import ASGD, Rprop, RAdam, NAdam  # noqa: F401
from .lbfgs import LBFGS  # noqa: F401
from . import lr  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
