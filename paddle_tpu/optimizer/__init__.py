"""paddle.optimizer analog."""
from .optimizer import (  # noqa: F401
    Optimizer, SGD, Momentum, Adam, AdamW, Adagrad, RMSProp, Adadelta, Adamax, Lamb,
    L2Decay, L1Decay,
)
from . import lr  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
