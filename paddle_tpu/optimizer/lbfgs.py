"""L-BFGS optimizer (closure-based full-batch quasi-Newton).

Reference: python/paddle/optimizer/lbfgs.py — limited-memory BFGS with two-loop
recursion over (s, y) history and optional strong-Wolfe cubic line search;
`step(closure)` re-evaluates the loss/gradients as the line search probes points.
Host-side driver logic (the search is inherently sequential); the closure itself
runs whatever jitted compute the model uses.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import enable_grad, no_grad
from .optimizer import Optimizer


def _cubic_interpolate(x1, f1, g1, x2, f2, g2, bounds=None):
    if bounds is not None:
        xmin_bound, xmax_bound = bounds
    else:
        xmin_bound, xmax_bound = (x1, x2) if x1 <= x2 else (x2, x1)
    d1 = g1 + g2 - 3 * (f1 - f2) / (x1 - x2)
    d2_square = d1**2 - g1 * g2
    if d2_square >= 0:
        d2 = d2_square**0.5
        if x1 <= x2:
            min_pos = x2 - (x2 - x1) * ((g2 + d2 - d1) / (g2 - g1 + 2 * d2))
        else:
            min_pos = x1 - (x1 - x2) * ((g1 + d2 - d1) / (g1 - g2 + 2 * d2))
        return min(max(min_pos, xmin_bound), xmax_bound)
    return (xmin_bound + xmax_bound) / 2.0


def _strong_wolfe(obj_func, x, t, d, f, g, gtd, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    d_norm = float(np.abs(d).max())
    g = g.copy()
    f_new, g_new = obj_func(x, t, d)
    ls_func_evals = 1
    gtd_new = float(np.dot(g_new, d))

    t_prev, f_prev, g_prev, gtd_prev = 0.0, f, g, gtd
    done = False
    ls_iter = 0
    bracket = bracket_f = bracket_g = bracket_gtd = None
    while ls_iter < max_ls:
        if f_new > (f + c1 * t * gtd) or (ls_iter > 1 and f_new >= f_prev):
            bracket = [t_prev, t]
            bracket_f = [f_prev, f_new]
            bracket_g = [g_prev, g_new.copy()]
            bracket_gtd = [gtd_prev, gtd_new]
            break
        if abs(gtd_new) <= -c2 * gtd:
            bracket = [t, t]
            bracket_f = [f_new, f_new]
            bracket_g = [g_new, g_new]
            bracket_gtd = [gtd_new, gtd_new]
            done = True
            break
        if gtd_new >= 0:
            bracket = [t_prev, t]
            bracket_f = [f_prev, f_new]
            bracket_g = [g_prev, g_new.copy()]
            bracket_gtd = [gtd_prev, gtd_new]
            break
        min_step = t + 0.01 * (t - t_prev)
        max_step = t * 10
        tmp = t
        t = _cubic_interpolate(t_prev, f_prev, gtd_prev, t, f_new, gtd_new,
                               bounds=(min_step, max_step))
        t_prev, f_prev, g_prev, gtd_prev = tmp, f_new, g_new.copy(), gtd_new
        f_new, g_new = obj_func(x, t, d)
        ls_func_evals += 1
        gtd_new = float(np.dot(g_new, d))
        ls_iter += 1

    if ls_iter == max_ls:
        bracket = [0.0, t]
        bracket_f = [f, f_new]
        bracket_g = [g, g_new]
        bracket_gtd = [gtd, gtd_new]

    # zoom phase
    insuf_progress = False
    low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[-1] else (1, 0)
    while not done and ls_iter < max_ls:
        if abs(bracket[1] - bracket[0]) * d_norm < tolerance_change:
            break
        t = _cubic_interpolate(bracket[0], bracket_f[0], bracket_gtd[0],
                               bracket[1], bracket_f[1], bracket_gtd[1])
        eps = 0.1 * (max(bracket) - min(bracket))
        if min(max(bracket) - t, t - min(bracket)) < eps:
            if insuf_progress or t >= max(bracket) or t <= min(bracket):
                if abs(t - max(bracket)) < abs(t - min(bracket)):
                    t = max(bracket) - eps
                else:
                    t = min(bracket) + eps
                insuf_progress = False
            else:
                insuf_progress = True
        else:
            insuf_progress = False
        f_new, g_new = obj_func(x, t, d)
        ls_func_evals += 1
        gtd_new = float(np.dot(g_new, d))
        ls_iter += 1
        if f_new > (f + c1 * t * gtd) or f_new >= bracket_f[low_pos]:
            bracket[high_pos] = t
            bracket_f[high_pos] = f_new
            bracket_g[high_pos] = g_new.copy()
            bracket_gtd[high_pos] = gtd_new
            low_pos, high_pos = (0, 1) if bracket_f[0] <= bracket_f[1] else (1, 0)
        else:
            if abs(gtd_new) <= -c2 * gtd:
                done = True
            elif gtd_new * (bracket[high_pos] - bracket[low_pos]) >= 0:
                bracket[high_pos] = bracket[low_pos]
                bracket_f[high_pos] = bracket_f[low_pos]
                bracket_g[high_pos] = bracket_g[low_pos]
                bracket_gtd[high_pos] = bracket_gtd[low_pos]
            bracket[low_pos] = t
            bracket_f[low_pos] = f_new
            bracket_g[low_pos] = g_new.copy()
            bracket_gtd[low_pos] = gtd_new

    t = bracket[low_pos]
    f_new = bracket_f[low_pos]
    g_new = bracket_g[low_pos]
    return f_new, g_new, t, ls_func_evals


class LBFGS(Optimizer):
    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision=False, name=name)
        self.max_iter = max_iter
        self.max_eval = max_eval
        self.tolerance_grad = tolerance_grad
        self.tolerance_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._state = {"func_evals": 0, "n_iter": 0}

    # flat host-side views ----------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list if not p.stop_gradient]

    def _gather_flat_grad(self):
        views = []
        for p in self._params():
            g = p.grad
            views.append(np.zeros(int(np.prod(p.shape)), np.float64)
                         if g is None else
                         np.asarray(g._value, np.float64).ravel())
        return np.concatenate(views) if views else np.zeros(0)

    def _flat_params(self):
        return np.concatenate(
            [np.asarray(p._value, np.float64).ravel() for p in self._params()])

    def _set_flat_params(self, flat):
        offset = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            chunk = flat[offset:offset + n].reshape(p.shape)
            p._value = jnp.asarray(chunk, p._value.dtype)
            offset += n

    def _directional_evaluate(self, closure, x, t, d):
        self._set_flat_params(x + t * d)
        loss = float(closure())
        flat_grad = self._gather_flat_grad()
        self._set_flat_params(x)
        return loss, flat_grad

    @no_grad()
    def step(self, closure):
        state = self._state

        def with_grad_closure():
            with enable_grad():
                return closure()

        orig_loss = with_grad_closure()
        loss = float(orig_loss)
        current_evals = 1
        state["func_evals"] += 1

        flat_grad = self._gather_flat_grad()
        if float(np.abs(flat_grad).max() if flat_grad.size else 0.0) \
                <= self.tolerance_grad:
            return orig_loss

        d = state.get("d")
        t = state.get("t")
        old_sk = state.get("old_sk", [])
        old_yk = state.get("old_yk", [])
        ro = state.get("ro", [])
        H_diag = state.get("H_diag")
        prev_flat_grad = state.get("prev_flat_grad")
        prev_loss = state.get("prev_loss")

        n_iter = 0
        lr = self.get_lr()
        while n_iter < self.max_iter:
            n_iter += 1
            state["n_iter"] += 1
            if state["n_iter"] == 1:
                d = -flat_grad
                old_sk, old_yk, ro = [], [], []
                H_diag = 1.0
            else:
                y = flat_grad - prev_flat_grad
                s = d * t
                ys = float(np.dot(y, s))
                if ys > 1e-10:
                    if len(old_yk) == self.history_size:
                        old_yk.pop(0)
                        old_sk.pop(0)
                        ro.pop(0)
                    old_yk.append(y)
                    old_sk.append(s)
                    ro.append(1.0 / ys)
                    H_diag = ys / float(np.dot(y, y))
                num_old = len(old_yk)
                al = [0.0] * num_old
                q = -flat_grad
                for i in range(num_old - 1, -1, -1):
                    al[i] = float(np.dot(old_sk[i], q)) * ro[i]
                    q = q - al[i] * old_yk[i]
                d = q * H_diag
                for i in range(num_old):
                    be_i = float(np.dot(old_yk[i], d)) * ro[i]
                    d = d + old_sk[i] * (al[i] - be_i)

            prev_flat_grad = flat_grad.copy()
            prev_loss = loss

            # learning-rate selection
            if state["n_iter"] == 1:
                t = min(1.0, 1.0 / float(np.abs(flat_grad).sum())) * lr
            else:
                t = lr

            gtd = float(np.dot(flat_grad, d))
            if gtd > -self.tolerance_change:
                break

            ls_func_evals = 0
            if self.line_search_fn is not None:
                if self.line_search_fn != "strong_wolfe":
                    raise RuntimeError(
                        "only 'strong_wolfe' is supported for line_search_fn")
                x_init = self._flat_params()

                def obj_func(x, t, d):
                    return self._directional_evaluate(
                        with_grad_closure, x, t, d)

                loss, flat_grad, t, ls_func_evals = _strong_wolfe(
                    obj_func, x_init, t, d, loss, flat_grad, gtd,
                    tolerance_change=self.tolerance_change)
                self._set_flat_params(x_init + t * d)
            else:
                self._set_flat_params(self._flat_params() + t * d)
                if n_iter != self.max_iter:
                    loss = float(with_grad_closure())
                    flat_grad = self._gather_flat_grad()
                    ls_func_evals = 1

            current_evals += ls_func_evals
            state["func_evals"] += ls_func_evals
            if n_iter == self.max_iter or current_evals >= self.max_eval:
                break
            if float(np.abs(flat_grad).max() if flat_grad.size else 0.0) \
                    <= self.tolerance_grad:
                break
            if float(np.abs(d * t).max()) <= self.tolerance_change:
                break
            if abs(loss - prev_loss) < self.tolerance_change:
                break

        state.update({"d": d, "t": t, "old_sk": old_sk, "old_yk": old_yk,
                      "ro": ro, "H_diag": H_diag,
                      "prev_flat_grad": prev_flat_grad, "prev_loss": prev_loss})
        return orig_loss

    def state_dict(self):
        return {"state": dict(self._state)}

    def set_state_dict(self, state):
        if "state" in state:
            self._state.update(state["state"])
