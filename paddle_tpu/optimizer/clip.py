"""Gradient clipping (reference: python/paddle/nn/clip.py — ClipGradByValue/
ByNorm/ByGlobalNorm; hybrid-parallel variant in fleet HybridParallelClipGrad).

Clips are pure functions over grad pytrees so they run inside the jitted optimizer
update (one fused kernel chain) in both eager and compiled training.
"""
from __future__ import annotations

import jax.numpy as jnp


class ClipGradBase:
    def apply(self, params, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def apply(self, params, grads):
        return [jnp.clip(g, self.min, self.max) if g is not None else None
                for g in grads]


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2 norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, params, grads):
        out = []
        for g in grads:
            if g is None:
                out.append(None)
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((g.astype(jnp.float32) * scale).astype(g.dtype))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Global L2 norm clip across all grads (fp32 accumulation)."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def global_norm(self, grads):
        sq = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads if g is not None]
        if not sq:
            return jnp.asarray(0.0, jnp.float32)
        return jnp.sqrt(sum(sq))

    def apply(self, params, grads):
        norm = self.global_norm(grads)
        scale = self.clip_norm / jnp.maximum(norm, self.clip_norm)
        return [(g.astype(jnp.float32) * scale).astype(g.dtype)
                if g is not None else None for g in grads]
