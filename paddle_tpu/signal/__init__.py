"""paddle.signal analog — frame / overlap_add / stft / istft.

Reference: python/paddle/signal.py (frame:33, overlap_add:177, stft:296, istft:442,
lowering to phi frame/overlap_add kernels + fft). TPU-native: framing is a gather with
static frame indices (XLA turns it into a strided slice loop fused with the FFT); all
four functions are pure jax and dispatch through the tape.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_val(v, frame_length, hop_length, axis=-1):
    if axis not in (-1, 0):
        raise ValueError("axis must be 0 or -1")
    n = v.shape[axis]
    if frame_length > n:
        raise ValueError(f"frame_length ({frame_length}) > signal length ({n})")
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # (F, L)
    if axis == -1:
        out = jnp.take(v, idx, axis=-1)              # (..., F, L)
        return jnp.swapaxes(out, -1, -2)             # (..., L, F) — paddle layout
    out = jnp.take(v, idx.T, axis=0)                 # (L, F, ...)
    return out


def frame(x, frame_length, hop_length, axis=-1, name=None):
    def fn(v):
        return _frame_val(v, frame_length, hop_length, axis)

    return dispatch(fn, (x,), {}, name="frame")


def _overlap_add_val(v, hop_length, axis=-1):
    if axis not in (-1, 0):
        raise ValueError("axis must be 0 or -1")
    if axis == 0:
        v = jnp.moveaxis(v, 1, -1)
        v = jnp.moveaxis(v, 0, -2)  # (..., L, F) view with leading batch at the end
        res = _overlap_add_val(v, hop_length, axis=-1)
        return jnp.moveaxis(res, -1, 0)
    # v: (..., frame_length, num_frames)
    frame_length, num_frames = v.shape[-2], v.shape[-1]
    out_len = (num_frames - 1) * hop_length + frame_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[None, :] + jnp.arange(frame_length)[:, None]  # (L, F)
    flat_idx = idx.reshape(-1)
    batch = v.shape[:-2]
    vf = v.reshape(batch + (frame_length * num_frames,))
    out = jnp.zeros(batch + (out_len,), dtype=v.dtype)
    return out.at[..., flat_idx].add(vf)


def overlap_add(x, hop_length, axis=-1, name=None):
    def fn(v):
        return _overlap_add_val(v, hop_length, axis)

    return dispatch(fn, (x,), {}, name="overlap_add")


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform, paddle.signal.stft parity (signal.py:296)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win_val = window._value if isinstance(window, Tensor) else window

    def fn(v, w):
        if w is None:
            w = jnp.ones((win_length,), dtype=v.dtype)
        pad = (n_fft - win_length) // 2
        if pad:
            w = jnp.pad(w, (pad, n_fft - win_length - pad))
        sig = v
        if center:
            widths = [(0, 0)] * (sig.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            sig = jnp.pad(sig, widths, mode=pad_mode)
        frames = _frame_val(sig, n_fft, hop_length, axis=-1)   # (..., n_fft, F)
        frames = frames * w[:, None]
        if onesided:
            spec = jnp.fft.rfft(frames, axis=-2)
        else:
            spec = jnp.fft.fft(frames, axis=-2)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, dtype=spec.real.dtype))
        return spec

    return dispatch(fn, (x, win_val), {}, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with window-envelope normalization (signal.py:442)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win_val = window._value if isinstance(window, Tensor) else window

    def fn(spec, w):
        rdtype = jnp.real(spec).dtype
        if w is None:
            w = jnp.ones((win_length,), dtype=rdtype)
        pad = (n_fft - win_length) // 2
        if pad:
            w = jnp.pad(w, (pad, n_fft - win_length - pad))
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, dtype=rdtype))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-2)
        else:
            frames = jnp.fft.ifft(spec, axis=-2)
            if not return_complex:
                frames = jnp.real(frames)
        frames = frames * w[:, None]
        sig = _overlap_add_val(frames, hop_length, axis=-1)
        env = _overlap_add_val(
            jnp.broadcast_to((w * w)[:, None], frames.shape[-2:]).astype(rdtype),
            hop_length, axis=-1)
        sig = sig / jnp.where(env > 1e-11, env, 1.0)
        if center:
            sig = sig[..., n_fft // 2: sig.shape[-1] - n_fft // 2]
        if length is not None:
            if sig.shape[-1] >= length:
                sig = sig[..., :length]
            else:
                widths = [(0, 0)] * (sig.ndim - 1) + [(0, length - sig.shape[-1])]
                sig = jnp.pad(sig, widths)
        return sig

    return dispatch(fn, (x, win_val), {}, name="istft")
