"""paddle.tensor namespace — the flat tensor-op API.

Reference: python/paddle/tensor/ (creation/math/manipulation/linalg/logic/einsum
modules re-exported at paddle top level). Implementations live in paddle_tpu/ops/;
this module mirrors the reference's namespace so `paddle.tensor.xxx` call sites port
directly.
"""
from ..ops.creation import *  # noqa: F401,F403
from ..ops.math import *  # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.linalg import *  # noqa: F401,F403
from ..ops.logic import *  # noqa: F401,F403
from ..ops.einsum import einsum  # noqa: F401
from ..ops.creation import to_tensor, assign  # noqa: F401
from ..ops.array import (array_length, array_read, array_write,  # noqa: F401
                         create_array)
