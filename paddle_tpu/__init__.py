"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new design with the capability surface of the reference (PaddlePaddle, mounted at
/root/reference — see SURVEY.md): eager tensors with tape autograd, a jit/compile path,
nn/optimizer/amp/io stacks, and a first-class distributed story (DP/TP/PP/SP/EP, ZeRO,
DTensor-style semi-auto sharding, sharded checkpoints) — all riding JAX/XLA/Pallas/pjit
instead of CUDA/NCCL.
"""
from __future__ import annotations

import jax as _jax

# float64/int64 parity with the reference (paddle supports fp64; indices are int64).
# TPU code paths use fp32/bf16 throughout; fp64 arrays are CPU-only like the reference's
# CPU-only kernels.
_jax.config.update("jax_enable_x64", True)

import numpy as _np  # noqa: E402

from .core import dtype as _dtype_mod  # noqa: E402
from .core.dtype import (  # noqa: E402,F401
    bool_ as bool, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype, finfo, iinfo,
)
from .core.tensor import (  # noqa: E402,F401
    Tensor, no_grad, enable_grad, is_grad_enabled, set_grad_enabled, dispatch,
    register_op,
)
from .core.device import (  # noqa: E402,F401
    CPUPlace, TPUPlace, CUDAPlace, XPUPlace, CustomPlace, Place,
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
)
from .core.random import seed, get_rng_state, set_rng_state, Generator  # noqa: E402,F401
from .core.flags import get_flags, set_flags  # noqa: E402,F401

from .ops import *  # noqa: E402,F401,F403
from . import ops as _ops  # noqa: E402
from .autograd import grad, PyLayer  # noqa: E402,F401
from .ops.logic import is_tensor  # noqa: E402,F401

__version__ = "0.1.0"

# ---------------------------------------------------------------------------
# lazy subpackages (keeps import light and cycle-free)
# ---------------------------------------------------------------------------
_LAZY_SUBMODULES = (
    "nn", "optimizer", "autograd", "amp", "jit", "io", "distributed", "vision",
    "static", "device", "profiler", "metric", "hapi", "incubate", "utils", "text",
    "sparse", "linalg", "fft", "signal", "distribution", "audio", "geometric",
    "tensor", "regularizer", "quantization", "inference", "onnx", "serving",
)


_LAZY_ATTRS = {"Model": ("hapi", "Model"), "summary": ("hapi", "summary")}


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib
        try:
            mod = importlib.import_module(f".{name}", __name__)
        except ModuleNotFoundError as e:
            # keep hasattr() probes working when an optional subpackage is absent
            if e.name == f"{__name__}.{name}":
                raise AttributeError(
                    f"module 'paddle_tpu' has no attribute {name!r}") from None
            raise
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        import importlib
        mod_name, attr = _LAZY_ATTRS[name]
        val = getattr(importlib.import_module(f".{mod_name}", __name__), attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


# ---------------------------------------------------------------------------
# framework io (paddle.save / paddle.load)
# ---------------------------------------------------------------------------

def save(obj, path, protocol=4):
    from .framework_io import save as _save
    return _save(obj, path, protocol)


def load(path, **kwargs):
    from .framework_io import load as _load
    return _load(path, **kwargs)


# ---------------------------------------------------------------------------
# Tensor method surface
# ---------------------------------------------------------------------------

def _to_t(v):
    return v if isinstance(v, Tensor) else _ops.to_tensor(v)


def _bind(name, fn):
    setattr(Tensor, name, fn)


def _method(op_fn):
    def m(self, *args, **kwargs):
        return op_fn(self, *args, **kwargs)
    return m


def _inplace(op_fn):
    def m(self, *args, **kwargs):
        out = op_fn(self, *args, **kwargs)
        self._value = out._value
        self._node = out._node
        self._out_index = out._out_index
        if not out.stop_gradient:
            self.stop_gradient = False
        return self
    return m


_METHOD_NAMES = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder", "mod",
    "pow", "maximum", "minimum", "fmax", "fmin", "abs", "neg", "sign", "floor",
    "ceil", "round", "trunc", "frac", "exp", "expm1", "log", "log2", "log10",
    "log1p", "sqrt", "rsqrt", "sin", "cos", "tan", "asin", "acos", "atan", "sinh",
    "cosh", "tanh", "asinh", "acosh", "atanh", "reciprocal", "square", "erf",
    "erfinv", "lgamma", "digamma", "angle", "conj", "rad2deg", "deg2rad", "lerp",
    "clip", "scale", "stanh", "atan2", "heaviside", "hypot", "isnan", "isinf",
    "isfinite", "nan_to_num", "sigmoid", "logaddexp",
    # reductions
    "sum", "mean", "prod", "max", "min", "amax", "amin", "std", "var", "median",
    "nanmedian", "nansum", "nanmean", "quantile", "logsumexp", "all", "any",
    "count_nonzero", "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp",
    # linalg
    "matmul", "mm", "bmm", "mv", "dot", "norm", "dist", "cross", "cholesky",
    "inverse", "det", "t", "trace", "diagonal",
    # manipulation
    "reshape", "flatten", "squeeze", "unsqueeze", "transpose", "moveaxis",
    "swapaxes", "split", "chunk", "unbind", "tile", "expand", "expand_as",
    "broadcast_to", "flip", "rot90", "roll", "repeat_interleave", "gather",
    "gather_nd", "take_along_axis", "put_along_axis", "index_select",
    "index_sample", "index_add", "masked_select", "masked_fill", "scatter",
    "scatter_nd_add", "cast", "astype", "tensor_split", "as_strided",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "mode",
    "searchsorted", "bucketize", "unique", "unique_consecutive", "bincount",
    "tril", "triu", "where", "nonzero",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "isclose",
    "allclose", "equal_all",
]

for _name in _METHOD_NAMES:
    if hasattr(_ops, _name):
        _bind(_name, _method(getattr(_ops, _name)))

_INPLACE_NAMES = [
    "add", "subtract", "multiply", "divide", "clip", "scale", "floor", "ceil",
    "round", "exp", "sqrt", "rsqrt", "reciprocal", "tanh", "sigmoid", "abs",
    "remainder", "pow", "cast", "squeeze", "unsqueeze", "reshape", "flatten",
    "tril", "triu", "masked_fill", "scatter", "index_add", "index_put", "lerp",
    "put_along_axis",
]
for _name in _INPLACE_NAMES:
    if hasattr(_ops, _name):
        _bind(_name + "_", _inplace(getattr(_ops, _name)))


# module-level in-place forms the reference exports in paddle.__all__
# (python/paddle/__init__.py: index_add_, index_put_) — thin wrappers over
# the bound Tensor methods
def index_add_(x, index, axis, value, name=None):
    return x.index_add_(index, axis, value)


def index_put_(x, indices, value, accumulate=False, name=None):
    return x.index_put_(indices, value, accumulate)


def _fill_(self, value):
    import jax.numpy as jnp
    self._value = jnp.full_like(self._value, value)
    return self


def _zero_(self):
    return _fill_(self, 0)


def _uniform_(self, min=-1.0, max=1.0):
    import jax.numpy as jnp
    from .core import random as _random
    self._value = _jax.random.uniform(_random.next_key(), self._value.shape,
                                      dtype=self._value.dtype, minval=min, maxval=max)
    return self


def _normal_(self, mean=0.0, std=1.0):
    from .core import random as _random
    self._value = (mean + std * _jax.random.normal(
        _random.next_key(), self._value.shape, dtype=self._value.dtype))
    return self


_bind("fill_", _fill_)
_bind("zero_", _zero_)
_bind("uniform_", _uniform_)
_bind("normal_", _normal_)


# operators -----------------------------------------------------------------
def _binop(fn, swap=False):
    def m(self, other):
        if swap:
            return fn(_to_t(other), self)
        return fn(self, other)
    return m


_bind("__add__", _binop(_ops.add))
_bind("__radd__", _binop(_ops.add, swap=True))
_bind("__sub__", _binop(_ops.subtract))
_bind("__rsub__", _binop(_ops.subtract, swap=True))
_bind("__mul__", _binop(_ops.multiply))
_bind("__rmul__", _binop(_ops.multiply, swap=True))
_bind("__truediv__", _binop(_ops.divide))
_bind("__rtruediv__", _binop(_ops.divide, swap=True))
_bind("__floordiv__", _binop(_ops.floor_divide))
_bind("__rfloordiv__", _binop(_ops.floor_divide, swap=True))
_bind("__mod__", _binop(_ops.remainder))
_bind("__rmod__", _binop(_ops.remainder, swap=True))
_bind("__pow__", _binop(_ops.pow))
_bind("__rpow__", _binop(_ops.pow, swap=True))
_bind("__matmul__", _binop(_ops.matmul))
_bind("__rmatmul__", _binop(_ops.matmul, swap=True))
_bind("__neg__", lambda self: _ops.neg(self))
_bind("__abs__", lambda self: _ops.abs(self))
_bind("__invert__", lambda self: _ops.logical_not(self)
      if self.dtype == _np.dtype(_np.bool_) else _ops.bitwise_not(self))
_bind("__eq__", _binop(_ops.equal))
_bind("__ne__", _binop(_ops.not_equal))
_bind("__lt__", _binop(_ops.less_than))
_bind("__le__", _binop(_ops.less_equal))
_bind("__gt__", _binop(_ops.greater_than))
_bind("__ge__", _binop(_ops.greater_equal))


def _and(self, other):
    if self.dtype == _np.dtype(_np.bool_):
        return _ops.logical_and(self, other)
    return _ops.bitwise_and(self, other)


def _or(self, other):
    if self.dtype == _np.dtype(_np.bool_):
        return _ops.logical_or(self, other)
    return _ops.bitwise_or(self, other)


def _xor(self, other):
    if self.dtype == _np.dtype(_np.bool_):
        return _ops.logical_xor(self, other)
    return _ops.bitwise_xor(self, other)


_bind("__and__", _and)
_bind("__or__", _or)
_bind("__xor__", _xor)
Tensor.__hash__ = lambda self: id(self)


def _norm_index(idx):
    """lists → arrays (fancy indexing); keep slices/Ellipsis/None/ints as-is."""
    import jax.numpy as jnp
    if isinstance(idx, list):
        return jnp.asarray(idx)
    if isinstance(idx, tuple):
        return tuple(_norm_index(e) for e in idx)
    return idx


def _getitem(self, idx):
    idx = _norm_index(idx)
    return dispatch(lambda v, i: v[i], (self, idx), {}, name="getitem")


def _setitem(self, idx, value):
    import jax.numpy as jnp
    idx = _norm_index(idx)

    def fn(v, i, val):
        val = jnp.asarray(val)
        return v.at[i].set(val.astype(v.dtype))
    out = dispatch(fn, (self, idx, value), {}, name="setitem")
    self._value = out._value
    self._node = out._node
    self._out_index = out._out_index
    if not out.stop_gradient:
        self.stop_gradient = False


_bind("__getitem__", _getitem)
_bind("__setitem__", _setitem)


def _tensor_backward(self, grad_tensor=None, retain_graph=False):
    from .autograd.backward import run_backward
    run_backward([self], [grad_tensor] if grad_tensor is not None else None,
                 retain_graph)


_bind("backward", _tensor_backward)


def _tensor_to(self, *args, **kwargs):
    """.to(dtype) / .to(place) / .to('tpu')"""
    out = self
    for a in list(args) + list(kwargs.values()):
        if isinstance(a, (str, _np.dtype)) and (
                isinstance(a, _np.dtype) or a in _dtype_mod._NAME_TO_DTYPE):
            out = _ops.cast(out, a)
        elif isinstance(a, type) or hasattr(a, "kind"):
            pass  # place moves are no-ops under a single default device
    return out


_bind("to", _tensor_to)
_bind("cpu", lambda self: self)
_bind("cuda", lambda self, *a, **k: self)
_bind("tpu", lambda self, *a, **k: self)
_bind("pin_memory", lambda self: self)

from . import version  # noqa: E402,F401
from . import callbacks  # noqa: E402,F401
from .core import string_tensor as strings  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import sysconfig  # noqa: E402,F401

# ---------------------------------------------------------------------------
# top-level API long tail (constants, aliases, in-place wrappers) — closes the
# reference's paddle.__all__ surface (python/paddle/__init__.py)
# ---------------------------------------------------------------------------
import math as _math  # noqa: E402

inf = float("inf")
nan = float("nan")
pi = _math.pi
e = _math.e
newaxis = None
dtype = _np.dtype  # paddle.dtype is the dtype type object

# ParamAttr / flops resolve lazily (importing nn eagerly would defeat the
# lazy-submodule design above)
_LAZY_ATTRS.update({
    "ParamAttr": ("nn", "ParamAttr"),
    "flops": ("utils", "flops"),
})


_TOPLEVEL_INPLACE = [
    "abs", "acos", "addmm", "asin", "atan", "cast", "ceil", "clip", "cos",
    "cumsum", "cumprod", "digamma", "divide", "equal", "erf", "exp", "expm1",
    "flatten", "floor", "floor_divide", "frac", "gcd", "lcm", "lgamma", "log",
    "log2", "log10", "log1p", "logical_and", "logical_or", "logical_not",
    "logit", "masked_fill", "mod", "multiply", "nan_to_num", "neg", "pow",
    "reciprocal", "remainder", "renorm", "reshape", "round", "rsqrt",
    "scatter", "sigmoid", "sin", "sinc", "sinh", "sqrt", "square", "squeeze",
    "subtract", "t", "tan", "tanh", "transpose", "tril", "triu", "trunc",
    "unsqueeze", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "bitwise_invert", "copysign", "gammainc", "gammaincc",
    "gammaln", "hypot", "i0", "ldexp", "less_equal", "less_than", "less",
    "greater_equal", "greater_than", "multigammaln", "polygamma", "not_equal",
    "floor_mod",
]
_TOPLEVEL_INPLACE += ["bitwise_left_shift", "bitwise_right_shift",
                      "masked_scatter"]
for _n in _TOPLEVEL_INPLACE:
    if hasattr(_ops, _n) and not hasattr(_ops, _n + "_"):
        # _inplace (Tensor-method factory above) writes back into the first
        # argument AND propagates stop_gradient — reuse it for the top level
        _fn = _inplace(getattr(_ops, _n))
        _fn.__name__ = _n + "_"
        globals()[_n + "_"] = _fn


def where_(condition, x=None, y=None, name=None):
    """In-place on x (reference: paddle.where_ mutates x, not the mask)."""
    out = _ops.where(condition, x, y)
    x._value = out._value
    x._node = out._node
    x._out_index = out._out_index
    if not out.stop_gradient:
        x.stop_gradient = False
    return x


def rank(x):
    return _ops.to_tensor(len(x.shape))


def shape(x):
    return _ops.to_tensor(_np.asarray(x.shape, dtype="int32"))


def tolist(x):
    return x.numpy().tolist()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


_static_mode = False


def enable_static():
    global _static_mode
    _static_mode = True


def disable_static():
    global _static_mode
    _static_mode = False


def in_dynamic_mode():
    return not _static_mode


def disable_signal_handler():
    pass  # no native signal handlers are installed


class LazyGuard:
    """Deferred parameter initialization (reference: python/paddle/base —
    LazyGuard / lazy_init). Under the guard, ``create_parameter`` produces
    ABSTRACT values (``jax.ShapeDtypeStruct``) and records the initializer;
    ``param.initialize()`` / ``layer.materialize()`` runs it later. An
    abstract model costs no host memory, which is what lets the full
    Llama-2-7B hybrid train step be AOT-compiled and memory-checked on a
    virtual mesh (tests/test_7b_scale.py) without a pod."""

    def __enter__(self):
        from .nn.layer_base import _LAZY_INIT
        _LAZY_INIT.depth += 1
        return self

    def __exit__(self, *exc):
        from .nn.layer_base import _LAZY_INIT
        _LAZY_INIT.depth -= 1
        return False


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from .nn.layer_base import Parameter
    from .nn.initializer import Constant, XavierNormal
    init = default_initializer or (Constant(0.0) if is_bias
                                   else XavierNormal())
    from .core.dtype import convert_dtype
    return Parameter(init(list(shape), convert_dtype(dtype)), name=name)


def batch(reader, batch_size, drop_last=False):
    """Deprecated reader-batching helper (reference: paddle.batch)."""
    def gen():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return gen


def check_shape(shape):
    for s in shape:
        if s is not None and s < -1:
            raise ValueError(f"invalid dim {s} in shape {shape}")


def get_cuda_rng_state():
    return get_rng_state()


def set_cuda_rng_state(state):
    set_rng_state(state)


def from_dlpack(capsule):
    from .utils import dlpack as _dl
    return _dl.from_dlpack(capsule)


def to_dlpack(x):
    from .utils import dlpack as _dl
    return _dl.to_dlpack(x)


class CUDAPinnedPlace:
    """Pinned host memory place (no CUDA here; host arrays are the analog)."""

    def __repr__(self):
        return "CUDAPinnedPlace"


_LAZY_ATTRS.update({
    "DataParallel": ("distributed", "DataParallel"),
})

# pstring/raw (prototype string-tensor dtypes) are intentionally absent: the
# TPU build has no StringTensor analog (SURVEY.md §2.2 marks them niche).


# ---------------------------------------------------------------------------
# Tensor method parity: the reference monkey-patches ~394 functions onto
# Tensor (python/paddle/tensor/__init__.py tensor_method_func). Bind every
# top-level op that is not yet a method; `_`-suffixed names write back into
# self via the _inplace factory above.
# ---------------------------------------------------------------------------
_TENSOR_METHOD_PARITY = [
    'create_parameter', 'ormqr', 'cov', 'corrcoef', 'cond', 'cauchy_',
    'geometric_', 'lstsq', 't_', 'cholesky_inverse', 'histogram',
    'histogram_bin_edges', 'histogramdd', 'matrix_power',
    'matrix_transpose', 'qr', 'householder_product', 'pca_lowrank',
    'svd_lowrank', 'eigvals', 'eigvalsh', 'asin_', 'cumsum_', 'cumprod_',
    'logit', 'logit_', 'increment', 'log_', 'log2_', 'log10_', 'multiplex',
    'sinc', 'square_', 'reduce_as', 'multigammaln', 'multigammaln_',
    'nan_to_num_', 'hypot_', 'block_diag', 'add_n', 'inner', 'outer',
    'floor_divide_', 'mod_', 'floor_mod', 'floor_mod_', 'log1p_', 'addmm',
    'addmm_', 'kron', 'isin', 'isneginf', 'isposinf', 'isreal',
    'broadcast_shape', 'neg_', 'negative', 'lgamma_', 'gammaincc',
    'gammaincc_', 'gammainc', 'gammainc_', 'equal_', 'greater_equal_',
    'greater_than_', 'is_empty', 'less_equal_', 'less_than_', 'less',
    'less_', 'logical_and_', 'logical_not_', 'logical_or_', 'not_equal_',
    'is_tensor', 'concat', 'reverse', 'scatter_nd', 'shard_index', 'slice',
    'slice_scatter', 'hsplit', 'dsplit', 'vsplit', 'tensordot', 'stack',
    'strided_slice', 'transpose_', 'tan_', 'unstack', 'where_',
    'nanquantile', 'is_complex', 'is_integer', 'rank', 'real', 'imag',
    'is_floating_point', 'gammaln', 'gammaln_', 'digamma_', 'trunc_',
    'frac_', 'bitwise_and_', 'bitwise_or_', 'bitwise_xor_', 'bitwise_not_',
    'bitwise_invert', 'bitwise_invert_', 'broadcast_tensors', 'eig',
    'multi_dot', 'solve', 'cholesky_solve', 'triangular_solve', 'lu',
    'lu_unpack', 'cdist', 'as_complex', 'as_real', 'gcd', 'gcd_', 'lcm',
    'lcm_', 'diff', 'select_scatter', 'bernoulli_', 'exponential_',
    'index_put', 'take', 'sgn', 'frexp', 'ldexp', 'ldexp_', 'trapezoid',
    'cumulative_trapezoid', 'polar', 'vander', 'nextafter', 'unflatten',
    'view', 'view_as', 'unfold', 'i0', 'i0_', 'i0e', 'i1', 'i1e',
    'polygamma', 'polygamma_', 'diag_embed', 'diagflat', 'multinomial',
    'pinv', 'renorm', 'renorm_', 'acos_', 'atan_', 'cos_', 'sin_', 'sinc_',
    'sinh_', 'diag', 'copysign', 'copysign_', 'bitwise_left_shift',
    'bitwise_left_shift_', 'bitwise_right_shift', 'bitwise_right_shift_',
    'index_fill', 'atleast_1d', 'atleast_2d', 'atleast_3d',
    'diagonal_scatter', 'masked_scatter', 'masked_scatter_', 'combinations',
    'signbit', 'log_normal_'
]

for _n in _TENSOR_METHOD_PARITY:
    if hasattr(Tensor, _n):
        continue
    _fn = globals().get(_n)
    if _fn is None or not callable(_fn):
        continue
    _bind(_n, _method(_fn))

# in-place variants whose base op exists but had no eager wrapper yet
for _n in ["logical_xor", "atanh", "erfinv", "cosh", "acosh", "asinh",
           "index_fill"]:
    if hasattr(Tensor, _n) and not hasattr(Tensor, _n + "_"):
        _base = globals().get(_n) or getattr(_ops, _n, None)
        if _base is not None:
            _ip = _inplace(_base)
            _ip.__name__ = _n + "_"
            _bind(_n + "_", _ip)
            globals()[_n + "_"] = _ip

def _stft_method(self, *a, **k):
    from .signal import stft as _stft
    return _stft(self, *a, **k)


def _istft_method(self, *a, **k):
    from .signal import istft as _istft
    return _istft(self, *a, **k)


_bind("stft", _stft_method)
_bind("istft", _istft_method)


def create_tensor(dtype, name=None, persistable=False):
    """reference: tensor/creation.py create_tensor — an empty typed tensor."""
    import jax.numpy as _jnp
    from .core.dtype import convert_dtype as _cd
    t = Tensor(_jnp.zeros((0,), _cd(dtype)), stop_gradient=True)
    t.name = name
    t.persistable = persistable
    return t


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """Nucleus sampling (reference: tensor/random.py top_p_sampling — GPU
    kernel): keep the smallest prefix of sorted probs with mass >= ps,
    renormalize, sample one id per row. Returns (values, ids)."""
    import jax as _jax
    import jax.numpy as _jnp
    from .core import random as _random
    if threshold is not None or topp_seed is not None or \
            k not in (0, None) or mode not in ("truncated", None) or \
            return_top:
        raise NotImplementedError(
            "top_p_sampling: threshold/topp_seed/k/mode/return_top are not "
            "supported on this backend; only plain nucleus sampling (use "
            "seed= for reproducibility)")
    key = _jax.random.PRNGKey(seed) if seed >= 0 else _random.next_key()

    def fn(probs, psv):
        order = _jnp.argsort(-probs, axis=-1)
        sp = _jnp.take_along_axis(probs, order, axis=-1)
        cum = _jnp.cumsum(sp, axis=-1)
        keep = (cum - sp) < psv.reshape(-1, 1)  # first index crossing ps kept
        masked = _jnp.where(keep, sp, 0.0)
        masked = masked / _jnp.sum(masked, axis=-1, keepdims=True)
        idx_sorted = _jax.random.categorical(key, _jnp.log(masked + 1e-20),
                                             axis=-1)
        ids = _jnp.take_along_axis(order, idx_sorted[:, None], axis=-1)
        vals = _jnp.take_along_axis(probs, ids, axis=-1)
        return vals, ids
    from .core.tensor import dispatch as _dispatch
    return _dispatch(fn, (x, ps), {}, name="top_p_sampling")


def _tensor_set_(self, source=None, shape=None, dtype=None):
    """reference: Tensor.set_ — re-point this tensor at source's data."""
    from .core.dtype import convert_dtype as _cd
    if source is not None:
        src = source._value if isinstance(source, Tensor) else source
        if shape is not None:
            src = src.reshape(shape)
        self._value = src.astype(_cd(dtype)) if dtype is not None else src
    elif shape is not None:
        import jax.numpy as _jnp
        self._value = _jnp.zeros(
            shape, _cd(dtype) if dtype is not None else self._value.dtype)
    self._node = None
    return self


def _tensor_resize_(self, shape, fill_zero=False):
    """reference: Tensor.resize_ — keep the flat prefix; growing beyond the
    current size requires fill_zero=True (reference raises otherwise)."""
    import numpy as _np
    import jax.numpy as _jnp
    n_new = int(_np.prod(shape)) if len(shape) else 1
    flat = self._value.reshape(-1)
    if n_new <= flat.shape[0]:
        self._value = flat[:n_new].reshape(shape)
    else:
        if not fill_zero:
            raise ValueError(
                "resize_: growing the tensor requires fill_zero=True")
        pad = _jnp.zeros((n_new - flat.shape[0],), flat.dtype)
        self._value = _jnp.concatenate([flat, pad]).reshape(shape)
    self._node = None
    return self


_bind("set_", _tensor_set_)
_bind("resize_", _tensor_resize_)
_bind("create_tensor", _method(lambda self, *a, **k: create_tensor(*a, **k)))
_bind("top_p_sampling", _method(top_p_sampling))
