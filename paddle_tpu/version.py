"""paddle.version analog (reference: generated python/paddle/version/__init__.py)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "tpu-native"
with_gpu = "OFF"   # device story is TPU via PJRT
cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
istaged = True


def show():
    print(f"paddle_tpu {full_version} (commit {commit}); backend: JAX/XLA TPU")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
