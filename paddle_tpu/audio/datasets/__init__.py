"""paddle.audio.datasets — ESC50 / TESS over local files.

Reference: python/paddle/audio/datasets/{esc50,tess}.py — download-and-parse
datasets feeding (feature, label) pairs. Zero-egress environment: these read
an already-downloaded archive directory (pass data_dir); the feature modes
('raw'/'mfcc'/'logmelspectrogram'/'melspectrogram'/'spectrogram') reuse
paddle_tpu.audio.features.
"""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset
from ..backends.wave_backend import load

__all__ = ["ESC50", "TESS"]


class AudioClassificationDataset(Dataset):
    """reference: datasets/dataset.py — files + labels, optional feature
    extraction per __getitem__."""

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **kwargs):
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.feat_config = kwargs
        self.sample_rate = sample_rate

    def __len__(self):
        return len(self.files)

    def _convert_to_record(self, idx):
        waveform, sr = load(self.files[idx])
        import paddle_tpu as paddle
        x = waveform
        if x.ndim > 1:
            x = x[0]
        if self.feat_type == "raw":
            feat = x
        else:
            from .. import features
            name = {"mfcc": "MFCC", "logmelspectrogram": "LogMelSpectrogram",
                    "melspectrogram": "MelSpectrogram",
                    "spectrogram": "Spectrogram"}[self.feat_type]
            extractor = getattr(features, name)(sr=sr, **self.feat_config)
            feat = extractor(x.reshape([1, -1]))[0]
        return feat, self.labels[idx]

    def __getitem__(self, idx):
        return self._convert_to_record(idx)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference: datasets/esc50.py). Expects
    data_dir/<name>.wav files named fold-clipid-take-target.wav."""

    def __init__(self, mode="train", split=1, feat_type="raw", data_dir=None,
                 archive=None, **kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                "ESC50 needs data_dir pointing at the extracted audio "
                "directory (no network access in this environment)")
        files, labels = [], []
        for fn in sorted(os.listdir(data_dir)):
            if not fn.endswith(".wav"):
                continue
            parts = fn[:-4].split("-")
            fold, target = int(parts[0]), int(parts[-1])
            train_cond = fold != split if mode == "train" else fold == split
            if train_cond:
                files.append(os.path.join(data_dir, fn))
                labels.append(target)
        super().__init__(files, labels, feat_type, **kwargs)


class TESS(AudioClassificationDataset):
    """TESS emotional speech (reference: datasets/tess.py). Expects
    data_dir/<speaker>_<word>_<emotion>.wav."""

    n_folds = 5
    emotions = ["angry", "disgust", "fear", "happy", "neutral", "ps", "sad"]

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, archive=None, **kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                "TESS needs data_dir pointing at the extracted audio "
                "directory (no network access in this environment)")
        all_files = []
        for root, _dirs, fns in os.walk(data_dir):
            for fn in sorted(fns):
                if fn.endswith(".wav"):
                    all_files.append(os.path.join(root, fn))
        files, labels = [], []
        for i, f in enumerate(all_files):
            emo = os.path.basename(f)[:-4].split("_")[-1].lower()
            if emo not in self.emotions:
                continue
            fold = i % n_folds + 1
            cond = fold != split if mode == "train" else fold == split
            if cond:
                files.append(f)
                labels.append(self.emotions.index(emo))
        super().__init__(files, labels, feat_type, **kwargs)
