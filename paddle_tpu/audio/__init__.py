"""paddle.audio analog — windows, spectral features, feature layers.

Reference: python/paddle/audio/ (functional/window.py get_window,
functional/functional.py hz_to_mel/mel_to_hz/compute_fbank_matrix/power_to_db/
create_dct, features/layers.py Spectrogram/MelSpectrogram/LogMelSpectrogram/
MFCC). TPU-native: everything lowers to the stft in paddle_tpu.signal (XLA FFT)
plus dense matmuls for the mel filterbank / DCT — MXU-friendly by construction.
"""
from . import functional  # noqa: F401
from .features import (  # noqa: F401
    Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC,
)

__all__ = ["functional", "Spectrogram", "MelSpectrogram", "LogMelSpectrogram",
           "MFCC"]

from . import backends  # noqa: E402,F401
from . import datasets  # noqa: E402,F401
from .backends import load, info, save  # noqa: E402,F401
