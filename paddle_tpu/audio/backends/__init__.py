"""paddle.audio.backends — wave-file IO backend registry.

Reference: python/paddle/audio/backends/ (wave_backend.py + optional
paddleaudio soundfile backend). The stdlib `wave` backend is always
available; `set_backend` accepts only backends in list_available_backends().
"""
from .wave_backend import AudioInfo, info, load, save  # noqa: F401

__all__ = ["get_current_backend", "list_available_backends", "set_backend"]

_CURRENT = "wave_backend"


def list_available_backends():
    backends = ["wave_backend"]
    try:
        import soundfile  # noqa: F401
        backends.append("soundfile")
    except ImportError:
        pass
    return backends


def get_current_backend():
    return _CURRENT


def set_backend(backend_name):
    global _CURRENT
    if backend_name not in list_available_backends():
        raise NotImplementedError(
            f"backend {backend_name} is not available; choose from "
            f"{list_available_backends()}")
    _CURRENT = backend_name
