"""stdlib-wave audio IO (reference: audio/backends/wave_backend.py)."""
from __future__ import annotations

import wave

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor


class AudioInfo:
    """reference: backends/backend.py AudioInfo."""

    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def _error_message():
    return ("only PCM16 WAV supported by the wave backend; install a "
            "soundfile-based backend for other formats")


def info(filepath):
    with wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8, "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """Returns (waveform Tensor, sample_rate). normalize=True -> float32 in
    [-1, 1]; else int16 passthrough (reference wave_backend.load)."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        ch = f.getnchannels()
        width = f.getsampwidth()
        if width != 2:
            raise ValueError(_error_message())
        f.setpos(frame_offset)
        n = num_frames if num_frames >= 0 else f.getnframes() - frame_offset
        raw = f.readframes(n)
    data = np.frombuffer(raw, np.int16).reshape(-1, ch)
    if normalize:
        data = (data / 32768.0).astype(np.float32)
    arr = data.T if channels_first else data
    return Tensor(jnp.asarray(arr)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_S", bits_per_sample=16):
    if bits_per_sample != 16:
        raise ValueError(_error_message())
    arr = np.asarray(src._value if isinstance(src, Tensor) else src)
    if channels_first:
        arr = arr.T
    if arr.dtype != np.int16:
        arr = np.clip(arr, -1.0, 1.0)
        arr = (arr * 32767.0).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(arr.shape[1] if arr.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(int(sample_rate))
        f.writeframes(arr.tobytes())
