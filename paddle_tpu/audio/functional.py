"""paddle.audio.functional analog.

Reference: python/paddle/audio/functional/{window.py,functional.py}.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, dispatch
from ..ops.creation import to_tensor

__all__ = [
    "get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
    "fft_frequencies", "compute_fbank_matrix", "power_to_db", "create_dct",
]


def _np_window(name, win_length, fftbins=True):
    n = win_length
    sym = not fftbins
    if name in ("hann", "hanning"):
        return np.hanning(n + 1)[:-1] if not sym else np.hanning(n)
    if name == "hamming":
        return np.hamming(n + 1)[:-1] if not sym else np.hamming(n)
    if name == "blackman":
        return np.blackman(n + 1)[:-1] if not sym else np.blackman(n)
    if name == "bartlett":
        return np.bartlett(n + 1)[:-1] if not sym else np.bartlett(n)
    if name in ("rect", "rectangular", "boxcar", "ones"):
        return np.ones(n)
    if name == "bohman":
        m = n + 1 if fftbins else n
        fac = np.abs(np.linspace(-1, 1, m))
        w = (1 - fac) * np.cos(np.pi * fac) + np.sin(np.pi * fac) / np.pi
        return w[:-1] if fftbins else w
    if name == "cosine":
        m = n + 1 if fftbins else n
        w = np.sin(np.pi / m * (np.arange(m) + 0.5))
        return w[:-1] if fftbins else w
    if name == "triang":
        m = n + 1 if fftbins else n
        k = np.arange(1, (m + 1) // 2 + 1)
        if m % 2 == 0:
            w = (2 * k - 1.0) / m
            w = np.concatenate([w, w[::-1]])
        else:
            w = 2 * k / (m + 1.0)
            w = np.concatenate([w, w[-2::-1]])
        return w[:-1] if fftbins else w
    raise ValueError(f"unsupported window {name!r}")


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Reference: audio/functional/window.py get_window."""
    if isinstance(window, tuple):
        name, *params = window
        if name == "gaussian":
            std = params[0]
            m = win_length + 1 if fftbins else win_length
            k = np.arange(m) - (m - 1) / 2
            w = np.exp(-0.5 * (k / std) ** 2)
            w = w[:-1] if fftbins else w
        elif name in ("exponential", "exp"):
            tau = params[-1] if params else 1.0
            m = win_length + 1 if fftbins else win_length
            k = np.abs(np.arange(m) - (m - 1) / 2)
            w = np.exp(-k / tau)
            w = w[:-1] if fftbins else w
        elif name == "taylor":
            # scipy.signal.windows.taylor (reference routes here): nbar
            # near-in sidelobes at -sll dB, normalized to unity center
            nbar = int(params[0]) if params else 4
            sll = float(params[1]) if len(params) > 1 else 30.0
            norm = bool(params[2]) if len(params) > 2 else True
            m = win_length + 1 if fftbins else win_length
            bb = 10.0 ** (sll / 20.0)
            a = np.arccosh(bb) / np.pi
            s2 = nbar ** 2 / (a ** 2 + (nbar - 0.5) ** 2)
            ma = np.arange(1, nbar, dtype=np.float64)
            fm = np.zeros(nbar - 1)
            signs = (-1.0) ** (ma + 1)
            m2 = ma ** 2
            for mi in range(len(ma)):
                numer = signs[mi] * np.prod(
                    1 - m2[mi] / s2 / (a ** 2 + (ma - 0.5) ** 2))
                denom = 2 * np.prod(
                    [1 - m2[mi] / m2[j] for j in range(len(ma)) if j != mi])
                fm[mi] = numer / denom

            def w_at(ns):
                return 1 + 2 * np.sum(
                    fm[:, None] * np.cos(
                        2 * np.pi * ma[:, None] * (ns - m / 2.0 + 0.5) / m),
                    axis=0)

            w = w_at(np.arange(m, dtype=np.float64))
            if norm:
                w /= w_at(np.array([(m - 1) / 2.0]))[0]
            w = w[:-1] if fftbins else w
        else:
            raise ValueError(f"unsupported window {window!r}")
    else:
        w = _np_window(window, win_length, fftbins)
    return to_tensor(w.astype(dtype))


def hz_to_mel(freq, htk=False):
    scalar = not isinstance(freq, Tensor)
    f = np.asarray(freq._value if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mel = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz)
                       / logstep, mel)
    return float(mel) if scalar and mel.ndim == 0 else to_tensor(mel)


def mel_to_hz(mel, htk=False):
    scalar = not isinstance(mel, Tensor)
    m = np.asarray(mel._value if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        f = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        f = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        f = np.where(m >= min_log_mel,
                     min_log_hz * np.exp(logstep * (m - min_log_mel)), f)
    return float(f) if scalar and f.ndim == 0 else to_tensor(f)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    low = hz_to_mel(f_min, htk)
    high = hz_to_mel(f_max, htk)
    mels = np.linspace(low, high, n_mels)
    return to_tensor(np.asarray(mel_to_hz(to_tensor(mels), htk)._value,
                                dtype=dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return to_tensor(np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Mel filterbank (n_mels, 1 + n_fft//2). Reference:
    audio/functional/functional.py compute_fbank_matrix (librosa-compatible)."""
    f_max = f_max or sr / 2.0
    fftfreqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
    mel_f = np.asarray(mel_frequencies(n_mels + 2, f_min, f_max, htk)._value,
                       dtype=np.float64)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2: n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return to_tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """Reference: audio/functional/functional.py power_to_db."""
    def fn(s):
        log_spec = 10.0 * (jnp.log10(jnp.maximum(amin, s))
                           - jnp.log10(jnp.maximum(amin, ref_value)))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
        return log_spec

    return dispatch(fn, (spect,), {}, name="power_to_db")


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II basis (n_mels, n_mfcc). Reference: functional.py create_dct."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return to_tensor(dct.astype(dtype))
