"""paddle.sysconfig — header/library paths for extension builds
(reference: python/paddle/sysconfig.py)."""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    import paddle_tpu
    return os.path.join(os.path.dirname(paddle_tpu.__file__), "include")


def get_lib():
    import paddle_tpu
    return os.path.join(os.path.dirname(paddle_tpu.__file__), "libs")
