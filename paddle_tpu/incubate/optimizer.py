"""Incubate optimizers: LookAhead and ModelAverage.

Reference: python/paddle/incubate/optimizer/{lookahead.py,modelaverage.py} —
wrapper optimizers over an inner optimizer: LookAhead keeps slow weights
synced every k steps; ModelAverage maintains running parameter sums applied
at eval time.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer


class LookAhead(Optimizer):
    """k-step lookahead (reference: lookahead.py LookAhead / Zhang et al.):
    fast weights step with the inner optimizer; every k steps
    slow += alpha * (fast - slow), fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not isinstance(k, int) or k <= 0:
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._parameter_list = inner_optimizer._parameter_list
        self._slow = {}
        self._k_step = 0

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        self.inner_optimizer.step()
        self._k_step += 1
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            if self._k_step == 1:
                # reference lookahead.py:284 — slow initialized from the
                # params after the first inner step. Copy: the inner
                # optimizer's jitted update donates param buffers, which
                # would invalidate a shared reference.
                self._slow[id(p)] = jnp.array(p._value, copy=True)
                continue
            if self._k_step % self.k:
                continue
            slow = self.alpha * p._value + (1 - self.alpha) * self._slow[id(p)]
            self._slow[id(p)] = slow
            p._value = jnp.array(slow, copy=True)

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state)


class ModelAverage(Optimizer):
    """Running parameter average applied at eval (reference: modelaverage.py
    ModelAverage): accumulates sum_1 / sum_2 / sum_3 windows; apply() swaps
    params for their window average, restore() swaps back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(0.0, parameters, None, None, False, name)
        self.avg_rate = average_window_rate
        self.min_window = min_average_window
        self.max_window = max_average_window
        self._acc = {}
        self._backup = {}

    def step(self):
        for p in self._parameter_list:
            if p.stop_gradient:
                continue
            acc = self._acc.setdefault(
                id(p), {"sum": np.zeros(p.shape, np.float64), "n": 0})
            acc["sum"] += np.asarray(p._value, np.float64)
            acc["n"] += 1
            window = max(self.min_window,
                         min(self.max_window, int(acc["n"] * self.avg_rate)))
            if acc["n"] > window:
                # restart the window from the running mean (reference's
                # sum_1/2/3 rotation keeps a bounded-window mean)
                mean = acc["sum"] / acc["n"]
                acc["sum"] = mean
                acc["n"] = 1

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for p in self._parameter_list:
            acc = self._acc.get(id(p))
            if acc is None or acc["n"] == 0:
                continue
            self._backup[id(p)] = p._value
            p._value = jnp.asarray(acc["sum"] / acc["n"], p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._value = self._backup.pop(id(p))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, None
