from . import models  # noqa: F401
from . import ps  # noqa: E402,F401
