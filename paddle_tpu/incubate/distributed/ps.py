"""Parameter-server analog — sharded sparse embedding tables.

Reference: paddle/fluid/distributed/ps/ (brpc services + sharded embedding
tables in ps/table/, pull/push sparse) and python/paddle/distributed/ps/.
TPU-native positioning: dense training state lives in device HBM under
jit/pjit; the PS pattern survives for HOST-side huge sparse embeddings
(recommendation workloads) that cannot fit a chip. Tables shard rows across
server workers by id hash; clients pull rows before the device step and push
gradients after.

Two transports with the same capability set (lazy row init, sparse
SGD/Adagrad/Adam update rules, save/load):
  * the original pure-Python path over paddle_tpu.distributed.rpc
    (`SparseTable`/`start_server`/`PSClient`), and
  * the NATIVE path — a C++ table node (csrc/ps_table.cc: thread-per-
    connection socket service, 64 lock-sharded row buckets, in-server sparse
    optimizers, deterministic hash-based lazy init) spoken to by
    `NativePSClient`, the analog of the reference's brpc_ps_server.cc +
    MemorySparseTable.

`DistributedEmbedding` is the training-side bridge: forward pulls the batch's
unique rows into a device tensor (the differentiable leaf), backward leaves
the row gradients on `.grad`, and `push_step()` sends them to the servers —
the pull_sparse/push_sparse cycle of the reference's async trainers
(fluid/framework/hogwild_worker.cc).
"""
from __future__ import annotations

import ctypes
import os
import socket
import struct

import numpy as np

from ... import distributed as dist
from ...core import native
from ...distributed import rpc

__all__ = ["SparseTable", "start_server", "PSClient", "shutdown",
           "NativePSServer", "NativePSClient", "DistributedEmbedding",
           "GeoSGDDenseSync"]

_TABLES: dict[str, "SparseTable"] = {}


class SparseTable:
    """One server's shard of a sparse embedding table (reference:
    ps/table/memory_sparse_table.cc — lazy rows + sparse optimizer)."""

    def __init__(self, name, dim, init_std=0.01, optimizer="sgd", lr=0.01,
                 seed=0):
        if optimizer not in ("sgd", "adagrad", "adam"):
            raise ValueError(f"unknown sparse optimizer {optimizer!r}")
        self.name = name
        self.dim = dim
        self.init_std = init_std
        self.optimizer = optimizer
        self.lr = lr
        self.rows: dict[int, np.ndarray] = {}
        self._accum: dict[int, np.ndarray] = {}  # adagrad accum / adam m
        self._accum2: dict[int, np.ndarray] = {}  # adam v
        self._steps: dict[int, int] = {}  # adam per-row t
        self._rng = np.random.default_rng(seed)

    def _row(self, rid: int) -> np.ndarray:
        row = self.rows.get(rid)
        if row is None:
            row = (self._rng.standard_normal(self.dim) * self.init_std) \
                .astype(np.float32)
            self.rows[rid] = row
        return row

    def pull(self, ids):
        return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        grads = np.asarray(grads, dtype=np.float32)
        for i, g in zip(ids, grads):
            rid = int(i)
            row = self._row(rid)
            if self.optimizer == "adagrad":
                acc = self._accum.setdefault(
                    rid, np.zeros(self.dim, np.float32))
                acc += g * g
                row -= self.lr * g / (np.sqrt(acc) + 1e-10)
            elif self.optimizer == "adam":
                m = self._accum.setdefault(rid, np.zeros(self.dim, np.float32))
                v = self._accum2.setdefault(
                    rid, np.zeros(self.dim, np.float32))
                t = self._steps.get(rid, 0) + 1
                self._steps[rid] = t
                m += (1 - 0.9) * (g - m)
                v += (1 - 0.999) * (g * g - v)
                row -= self.lr * (m / (1 - 0.9 ** t)) / (
                    np.sqrt(v / (1 - 0.999 ** t)) + 1e-8)
            else:  # sgd
                row -= self.lr * g
        return len(ids)

    def save(self, dirname):
        os.makedirs(dirname, exist_ok=True)
        ids = np.asarray(sorted(self.rows), dtype=np.int64)
        zeros = np.zeros((0, self.dim), np.float32)

        def stacked(d):
            return np.stack([d.get(int(i), np.zeros(self.dim, np.float32))
                             for i in ids]) if len(ids) else zeros

        np.savez(os.path.join(dirname, f"{self.name}.npz"), ids=ids,
                 vals=stacked(self.rows), accum=stacked(self._accum),
                 accum2=stacked(self._accum2),
                 steps=np.asarray([self._steps.get(int(i), 0) for i in ids],
                                  dtype=np.int64))

    def load(self, dirname):
        """Restore REPLACES all table state, optimizer slots included —
        matching the native node's semantics."""
        data = np.load(os.path.join(dirname, f"{self.name}.npz"))
        ids = data["ids"]
        self.rows = {int(i): v.copy() for i, v in zip(ids, data["vals"])}
        self._accum = {}
        self._accum2 = {}
        self._steps = {}
        if "accum" in data:  # older checkpoints lack slot arrays
            for i, a, a2, t in zip(ids, data["accum"], data["accum2"],
                                   data["steps"]):
                if a.any():
                    self._accum[int(i)] = a.copy()
                if a2.any():
                    self._accum2[int(i)] = a2.copy()
                if t:
                    self._steps[int(i)] = int(t)


# -- server-side RPC entry points (executed in the server worker) -----------

def _srv_create(name, dim, kwargs):
    _TABLES[name] = SparseTable(name, dim, **kwargs)
    return True


def _srv_pull(name, ids):
    return _TABLES[name].pull(ids)


def _srv_push(name, ids, grads):
    return _TABLES[name].push(ids, grads)


def _srv_save(name, dirname):
    _TABLES[name].save(dirname)
    return True


def _srv_load(name, dirname):
    _TABLES[name].load(dirname)
    return True


def start_server(name=None, rank=None, world_size=None, master_endpoint=None):
    """Run this process as a PS server worker (reference: fleet runtime
    the_one_ps server init). Registers under `name` and serves until
    rpc.shutdown()."""
    rpc.init_rpc(name or f"ps_server_{rank or 0}", rank=rank,
                 world_size=world_size, master_endpoint=master_endpoint)


def shutdown():
    rpc.shutdown()


class PSClient:
    """Client view: shards rows over server workers by id hash (reference:
    ps/service client + fleet pull_sparse/push_sparse)."""

    def __init__(self, server_names):
        self.servers = list(server_names)

    def _shard(self, ids):
        ids = np.asarray(ids, dtype=np.int64).ravel()
        owner = ids % len(self.servers)
        return ids, owner

    def create_table(self, name, dim, **kwargs):
        for s in self.servers:
            rpc.rpc_sync(s, _srv_create, args=(name, dim, kwargs))

    def pull_sparse(self, name, ids):
        ids_flat, owner = self._shard(ids)
        out = np.zeros((len(ids_flat), 0), np.float32)
        rows = None
        for si, s in enumerate(self.servers):
            sel = np.nonzero(owner == si)[0]
            if not len(sel):
                continue
            part = rpc.rpc_sync(s, _srv_pull, args=(name, ids_flat[sel]))
            if rows is None:
                rows = np.zeros((len(ids_flat), part.shape[1]), np.float32)
            rows[sel] = part
        if rows is None:
            rows = out
        return rows.reshape(tuple(np.shape(ids)) + (-1,))

    def push_sparse(self, name, ids, grads):
        ids_flat, owner = self._shard(ids)
        grads = np.asarray(grads, np.float32).reshape(len(ids_flat), -1)
        futures = []
        for si, s in enumerate(self.servers):
            sel = np.nonzero(owner == si)[0]
            if not len(sel):
                continue
            futures.append(rpc.rpc_async(
                s, _srv_push, args=(name, ids_flat[sel], grads[sel])))
        for f in futures:
            f.wait()

    def save(self, name, dirname):
        for s in self.servers:
            rpc.rpc_sync(s, _srv_save, args=(name, os.path.join(
                dirname, s)))

    def load(self, name, dirname):
        for s in self.servers:
            rpc.rpc_sync(s, _srv_load, args=(name, os.path.join(
                dirname, s)))


# ---------------------------------------------------------------------------
# Native transport — C++ table node (csrc/ps_table.cc)
# ---------------------------------------------------------------------------

_OP_CREATE, _OP_PULL, _OP_PUSH, _OP_SAVE, _OP_LOAD, _OP_STATS = 1, 2, 3, 4, 5, 6
_OP_PULL_NOINIT = 7


class NativePSServer:
    """In-process handle on a native table node (its service threads are C++,
    so serving is GIL-free even when started inside a trainer process)."""

    def __init__(self, host="127.0.0.1", port=0):
        lib = native.load()
        if lib is None:
            raise RuntimeError("native runtime library unavailable")
        self._lib = lib
        bound = ctypes.c_int(0)
        self._h = lib.pt_ps_server_start(host.encode(), int(port),
                                         ctypes.byref(bound))
        if not self._h:
            raise OSError(f"cannot bind PS server on {host}:{port}")
        self.host = host
        self.port = int(bound.value)

    @property
    def endpoint(self):
        return f"{self.host}:{self.port}"

    def stop(self):
        if self._h:
            self._lib.pt_ps_server_stop(self._h)
            self._h = None

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class _PSConn:
    """One blocking connection speaking the ps_table.cc protocol."""

    def __init__(self, endpoint):
        host, port = endpoint.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=60.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _recv_full(self, n):
        buf = bytearray(n)
        view = memoryview(buf)
        got = 0
        while got < n:
            r = self.sock.recv_into(view[got:], n - got)
            if r == 0:
                raise ConnectionError("PS server closed connection")
            got += r
        return bytes(buf)

    def _check_ok(self):
        ok = self._recv_full(1)[0]
        if not ok:
            (mlen,) = struct.unpack(">I", self._recv_full(4))
            raise RuntimeError(
                f"PS error: {self._recv_full(mlen).decode(errors='replace')}")

    def request(self, op, name, payload=b"", reply_fmt=None):
        nb = name.encode()
        self.sock.sendall(struct.pack(">BI", op, len(nb)) + nb + payload)
        self._check_ok()
        if reply_fmt == "rows":
            (dim,) = struct.unpack(">I", self._recv_full(4))
            return dim
        if reply_fmt == "stats":
            return struct.unpack(">QQ", self._recv_full(16))
        return None

    def recv_floats(self, count):
        return np.frombuffer(self._recv_full(count * 4), dtype=np.float32)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class NativePSClient:
    """Client over native table nodes; shards ids across endpoints by modulo,
    like the reference client shards over server instances."""

    def __init__(self, endpoints):
        self.endpoints = list(endpoints)
        self._conns = [None] * len(self.endpoints)
        self._dims: dict[str, int] = {}  # known table dims (for empty pulls)

    def _conn(self, i) -> _PSConn:
        if self._conns[i] is None:
            self._conns[i] = _PSConn(self.endpoints[i])
        return self._conns[i]

    def close(self):
        for c in self._conns:
            if c is not None:
                c.close()
        self._conns = [None] * len(self.endpoints)

    def create_table(self, name, dim, optimizer="sgd", lr=0.01,
                     init_std=0.01, seed=0):
        opt = {"sgd": 0, "adagrad": 1, "adam": 2}[optimizer]
        payload = struct.pack(">IBffQ", int(dim), opt, float(lr),
                              float(init_std), int(seed))
        for i in range(len(self.endpoints)):
            self._conn(i).request(_OP_CREATE, name, payload)
        self._dims[name] = int(dim)

    def _shard(self, ids):
        ids_flat = np.ascontiguousarray(
            np.asarray(ids, dtype=np.int64).ravel())
        owner = ids_flat % len(self.endpoints)
        return ids_flat, owner

    def pull_sparse(self, name, ids, init_missing=True):
        ids_flat, owner = self._shard(ids)
        rows = None
        op = _OP_PULL if init_missing else _OP_PULL_NOINIT
        for si in range(len(self.endpoints)):
            sel = np.nonzero(owner == si)[0]
            if not len(sel):
                continue
            part_ids = np.ascontiguousarray(ids_flat[sel])
            conn = self._conn(si)
            dim = conn.request(op, name,
                               struct.pack(">Q", len(part_ids))
                               + part_ids.tobytes(), reply_fmt="rows")
            part = conn.recv_floats(len(part_ids) * dim).reshape(-1, dim)
            self._dims[name] = dim
            if rows is None:
                rows = np.zeros((len(ids_flat), dim), np.float32)
            rows[sel] = part
        if rows is None:  # empty ids: use the known dim (reshape can't infer)
            rows = np.zeros((len(ids_flat), self._dims.get(name, 0)),
                            np.float32)
        return rows.reshape(tuple(np.shape(ids)) + (rows.shape[-1],))

    def push_sparse(self, name, ids, grads):
        ids_flat, owner = self._shard(ids)
        grads = np.asarray(grads, np.float32).reshape(len(ids_flat), -1)
        # The server validates the grad width itself (the PUSH header now
        # carries it); this client-side check is just the earlier, cheaper
        # error, against the known dim (learned from create_table / any
        # pull; fetched cheaply if unknown).
        dim = self._dims.get(name)
        if dim is None and len(ids_flat):
            self.pull_sparse(name, ids_flat[:1], init_missing=False)
            dim = self._dims.get(name)
        if dim is not None and grads.shape[1] != dim:
            raise ValueError(
                f"push_sparse(grads) last dim {grads.shape[1]} != table "
                f"{name!r} dim {dim}")
        for si in range(len(self.endpoints)):
            sel = np.nonzero(owner == si)[0]
            if not len(sel):
                continue
            part_ids = np.ascontiguousarray(ids_flat[sel])
            part_g = np.ascontiguousarray(grads[sel])
            # PUSH carries the grad width so the server can drain the
            # stream and reply an attributable error on unknown tables
            # or width mismatches (instead of dropping the connection)
            self._conn(si).request(
                _OP_PUSH, name,
                struct.pack(">QI", len(part_ids), grads.shape[1])
                + part_ids.tobytes() + part_g.tobytes())

    def _path_op(self, op, name, dirname):
        os.makedirs(dirname, exist_ok=True)
        for si in range(len(self.endpoints)):
            path = os.path.join(dirname, f"shard{si}.pstbl").encode()
            self._conn(si).request(op, name,
                                   struct.pack(">I", len(path)) + path)

    def save(self, name, dirname):
        self._path_op(_OP_SAVE, name, dirname)

    def load(self, name, dirname):
        self._path_op(_OP_LOAD, name, dirname)

    def stats(self, name):
        rows = 0
        bytes_ = 0
        for si in range(len(self.endpoints)):
            r, b = self._conn(si).request(_OP_STATS, name, reply_fmt="stats")
            rows += r
            bytes_ += b
        return {"rows": int(rows), "bytes": int(bytes_)}


# ---------------------------------------------------------------------------
# Training-side bridge
# ---------------------------------------------------------------------------

class DistributedEmbedding:
    """Embedding whose rows live on parameter servers (reference:
    fleet pull_sparse/push_sparse in the async trainers,
    fluid/framework/hogwild_worker.cc; layer analog
    paddle/incubate/distributed/fleet's distributed embedding).

    forward(ids) pulls the batch's unique rows into ONE device tensor that is
    the differentiable leaf; the device-side gather that fans rows out to
    positions stays inside the compiled step. After loss.backward(), call
    push_step() to send each pulled row's gradient back. Works with both
    PSClient (RPC) and NativePSClient.
    """

    def __init__(self, client, table_name, dim, optimizer="sgd", lr=0.01,
                 init_std=0.01, seed=0, create=True):
        self.client = client
        self.table_name = table_name
        self.dim = int(dim)
        if create:
            client.create_table(table_name, dim, optimizer=optimizer,
                                lr=lr, init_std=init_std, seed=seed)
        self._pending = []

    def __call__(self, ids):
        from ...ops import creation, manipulation

        ids_np = np.asarray(
            ids.numpy() if hasattr(ids, "numpy") else ids, dtype=np.int64)
        uniq, inverse = np.unique(ids_np, return_inverse=True)
        rows = self.client.pull_sparse(self.table_name, uniq)
        pulled = creation.to_tensor(rows.astype(np.float32),
                                    stop_gradient=False)
        self._pending.append((uniq, pulled))
        inv = creation.to_tensor(
            np.ascontiguousarray(inverse.reshape(-1), dtype=np.int64))
        out = manipulation.gather(pulled, inv)
        return manipulation.reshape(out, list(ids_np.shape) + [self.dim])

    forward = __call__

    def push_step(self, scale=1.0):
        """Push accumulated row gradients from every forward since the last
        push; the server applies its sparse optimizer rule."""
        for uniq, pulled in self._pending:
            g = pulled.grad
            if g is None:
                continue
            g_np = np.asarray(g.numpy(), dtype=np.float32)
            if scale != 1.0:
                g_np = g_np * scale
            self.client.push_sparse(self.table_name, uniq, g_np)
        self._pending.clear()


class GeoSGDDenseSync:
    """Geo-SGD asynchronous dense synchronization over a PS table
    (reference: the geo-SGD mode of python/paddle/distributed/ps and
    fleet's the_one_ps runtime — workers train locally and exchange
    parameter DELTAS through the server at a fixed cadence instead of
    synchronous all-reduce).

    The server holds the authoritative dense blob as one table row per
    parameter (sgd rule, lr=1): a worker pushes ``last_synced - local``
    (so the server applies ``+= local - last_synced``) and pulls the
    merged value. Works over either transport.
    """

    def __init__(self, client, layer, table_name="geo_dense", sync_every=8,
                 create=True):
        self.client = client
        self.table_name = table_name
        self.sync_every = int(sync_every)
        if self.sync_every < 1:
            raise ValueError(f"sync_every must be >= 1, got {sync_every}")
        self._params = [(name, p) for name, p in layer.named_parameters()
                        if not getattr(p, "stop_gradient", False)]
        self._dim = max(int(np.prod(p.shape)) for _, p in self._params)
        self._step = 0
        ids = np.arange(len(self._params))
        if create:
            client.create_table(table_name, self._dim, optimizer="sgd",
                                lr=1.0, init_std=0.0)
            # seed the server blob with this worker's init
            server = self.client.pull_sparse(self.table_name, ids)
            delta = np.zeros_like(server)
            for i, (_, p) in enumerate(self._params):
                flat = np.asarray(p.numpy(), np.float32).ravel()
                delta[i, :len(flat)] = server[i, :len(flat)] - flat
            client.push_sparse(table_name, ids, delta)
        else:
            # a joining worker adopts the server's parameters (geo-SGD
            # workers share one base; reference: init broadcast before
            # async training starts). Refuse an unseeded table — adopting
            # the lazy zero rows would silently train a zero network.
            if hasattr(self.client, "stats"):
                try:
                    rows = self.client.stats(table_name)["rows"]
                except RuntimeError as e:  # table doesn't exist yet
                    rows = -1
                    cause = e
                else:
                    cause = None
                if rows < len(self._params):
                    raise RuntimeError(
                        f"geo table {table_name!r} not seeded yet — start "
                        f"the create=True worker first") from cause
            self._adopt(self.client.pull_sparse(self.table_name, ids))
        self._last = self._snapshot()

    def _adopt(self, merged):
        from ...ops import creation
        for i, (_, p) in enumerate(self._params):
            n = int(np.prod(p.shape))
            p.set_value(creation.to_tensor(
                merged[i, :n].reshape(p.shape).astype(np.float32)))

    def _snapshot(self):
        return [np.asarray(p.numpy(), np.float32).ravel().copy()
                for _, p in self._params]

    def step(self):
        """Call once per local train step; pushes deltas and pulls the
        merged params every `sync_every` steps. Returns True on sync."""
        self._step += 1
        if self._step % self.sync_every:
            return False
        ids = np.arange(len(self._params))
        delta = np.zeros((len(self._params), self._dim), np.float32)
        for i, (last, (_, p)) in enumerate(zip(self._last, self._params)):
            cur = np.asarray(p.numpy(), np.float32).ravel()
            delta[i, :len(cur)] = last - cur  # sgd rule applies -= delta
        self.client.push_sparse(self.table_name, ids, delta)
        self._adopt(self.client.pull_sparse(self.table_name, ids))
        self._last = self._snapshot()
        return True
