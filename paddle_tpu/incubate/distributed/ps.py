"""Parameter-server analog — sharded sparse embedding tables over RPC.

Reference: paddle/fluid/distributed/ps/ (brpc services + sharded embedding
tables in ps/table/, pull/push sparse) and python/paddle/distributed/ps/.
TPU-native positioning: dense training state lives in device HBM under
jit/pjit; the PS pattern survives for HOST-side huge sparse embeddings
(recommendation workloads) that cannot fit a chip. Tables shard rows across
server workers by id hash; clients pull rows before the device step and push
gradients after — transport is paddle_tpu.distributed.rpc, bootstrap the
TCPStore.

This is the capability analog of the reference's PS (lazy row init, sparse
SGD/Adagrad update rules, save/load), not its brpc implementation.
"""
from __future__ import annotations

import os

import numpy as np

from ... import distributed as dist
from ...distributed import rpc

__all__ = ["SparseTable", "start_server", "PSClient", "shutdown"]

_TABLES: dict[str, "SparseTable"] = {}


class SparseTable:
    """One server's shard of a sparse embedding table (reference:
    ps/table/memory_sparse_table.cc — lazy rows + sparse optimizer)."""

    def __init__(self, name, dim, init_std=0.01, optimizer="sgd", lr=0.01,
                 seed=0):
        self.name = name
        self.dim = dim
        self.init_std = init_std
        self.optimizer = optimizer
        self.lr = lr
        self.rows: dict[int, np.ndarray] = {}
        self._accum: dict[int, np.ndarray] = {}  # adagrad state
        self._rng = np.random.default_rng(seed)

    def _row(self, rid: int) -> np.ndarray:
        row = self.rows.get(rid)
        if row is None:
            row = (self._rng.standard_normal(self.dim) * self.init_std) \
                .astype(np.float32)
            self.rows[rid] = row
        return row

    def pull(self, ids):
        return np.stack([self._row(int(i)) for i in ids])

    def push(self, ids, grads):
        grads = np.asarray(grads, dtype=np.float32)
        for i, g in zip(ids, grads):
            rid = int(i)
            row = self._row(rid)
            if self.optimizer == "adagrad":
                acc = self._accum.setdefault(
                    rid, np.zeros(self.dim, np.float32))
                acc += g * g
                row -= self.lr * g / (np.sqrt(acc) + 1e-10)
            else:  # sgd
                row -= self.lr * g
        return len(ids)

    def save(self, dirname):
        os.makedirs(dirname, exist_ok=True)
        ids = np.asarray(sorted(self.rows), dtype=np.int64)
        vals = np.stack([self.rows[int(i)] for i in ids]) if len(ids) \
            else np.zeros((0, self.dim), np.float32)
        np.savez(os.path.join(dirname, f"{self.name}.npz"), ids=ids,
                 vals=vals)

    def load(self, dirname):
        data = np.load(os.path.join(dirname, f"{self.name}.npz"))
        self.rows = {int(i): v.copy()
                     for i, v in zip(data["ids"], data["vals"])}


# -- server-side RPC entry points (executed in the server worker) -----------

def _srv_create(name, dim, kwargs):
    _TABLES[name] = SparseTable(name, dim, **kwargs)
    return True


def _srv_pull(name, ids):
    return _TABLES[name].pull(ids)


def _srv_push(name, ids, grads):
    return _TABLES[name].push(ids, grads)


def _srv_save(name, dirname):
    _TABLES[name].save(dirname)
    return True


def _srv_load(name, dirname):
    _TABLES[name].load(dirname)
    return True


def start_server(name=None, rank=None, world_size=None, master_endpoint=None):
    """Run this process as a PS server worker (reference: fleet runtime
    the_one_ps server init). Registers under `name` and serves until
    rpc.shutdown()."""
    rpc.init_rpc(name or f"ps_server_{rank or 0}", rank=rank,
                 world_size=world_size, master_endpoint=master_endpoint)


def shutdown():
    rpc.shutdown()


class PSClient:
    """Client view: shards rows over server workers by id hash (reference:
    ps/service client + fleet pull_sparse/push_sparse)."""

    def __init__(self, server_names):
        self.servers = list(server_names)

    def _shard(self, ids):
        ids = np.asarray(ids, dtype=np.int64).ravel()
        owner = ids % len(self.servers)
        return ids, owner

    def create_table(self, name, dim, **kwargs):
        for s in self.servers:
            rpc.rpc_sync(s, _srv_create, args=(name, dim, kwargs))

    def pull_sparse(self, name, ids):
        ids_flat, owner = self._shard(ids)
        out = np.zeros((len(ids_flat), 0), np.float32)
        rows = None
        for si, s in enumerate(self.servers):
            sel = np.nonzero(owner == si)[0]
            if not len(sel):
                continue
            part = rpc.rpc_sync(s, _srv_pull, args=(name, ids_flat[sel]))
            if rows is None:
                rows = np.zeros((len(ids_flat), part.shape[1]), np.float32)
            rows[sel] = part
        if rows is None:
            rows = out
        return rows.reshape(tuple(np.shape(ids)) + (-1,))

    def push_sparse(self, name, ids, grads):
        ids_flat, owner = self._shard(ids)
        grads = np.asarray(grads, np.float32).reshape(len(ids_flat), -1)
        futures = []
        for si, s in enumerate(self.servers):
            sel = np.nonzero(owner == si)[0]
            if not len(sel):
                continue
            futures.append(rpc.rpc_async(
                s, _srv_push, args=(name, ids_flat[sel], grads[sel])))
        for f in futures:
            f.wait()

    def save(self, name, dirname):
        for s in self.servers:
            rpc.rpc_sync(s, _srv_save, args=(name, os.path.join(
                dirname, s)))

    def load(self, name, dirname):
        for s in self.servers:
            rpc.rpc_sync(s, _srv_load, args=(name, os.path.join(
                dirname, s)))
