from .gates import NaiveGate, GShardGate, SwitchGate, BaseGate  # noqa: F401
from .moe_layer import MoELayer  # noqa: F401
