"""MoE router gates (reference: incubate/distributed/models/moe/gate/
{naive,gshard,switch}_gate.py).

A gate maps tokens [T, D] to router logits [T, E] and declares its top_k and
capacity policy; the dispatch/combine math itself lives in
ops/kernels/moe.py (static-shape GShard algorithm)."""
from __future__ import annotations

import math

from .....nn.layer_base import Layer
from .....nn.initializer import XavierUniform
from .....nn import functional as F


class BaseGate(Layer):
    def __init__(self, d_model, num_experts, top_k=2, capacity_factor=2.0,
                 eval_capacity_factor=None):
        super().__init__()
        self.d_model = d_model
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = (eval_capacity_factor
                                     if eval_capacity_factor is not None
                                     else capacity_factor)
        self.weight = self.create_parameter(
            (d_model, num_experts), default_initializer=XavierUniform())

    def forward(self, x):
        """Token features [T, D] -> router logits [T, E]."""
        return F.linear(x, self.weight)

    def effective_capacity_factor(self):
        return self.capacity_factor if self.training else self.eval_capacity_factor


class NaiveGate(BaseGate):
    """Plain linear router, top-k softmax weighting (reference naive_gate.py)."""


class GShardGate(BaseGate):
    """Top-2 gate with load-balance aux loss (reference gshard_gate.py)."""


class SwitchGate(BaseGate):
    """Top-1 (Switch Transformer) gate (reference switch_gate.py)."""

    def __init__(self, d_model, num_experts, top_k=1, capacity_factor=1.25,
                 eval_capacity_factor=2.0):
        super().__init__(d_model, num_experts, 1, capacity_factor,
                         eval_capacity_factor)
