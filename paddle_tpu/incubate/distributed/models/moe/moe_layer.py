"""MoELayer — expert-parallel mixture-of-experts (reference:
incubate/distributed/models/moe/moe_layer.py:261).

TPU-native deviations from the reference:
- experts are STACKED weight tensors ([E, D, F] / [E, F, D]) rather than a
  python list of sub-Layers — one einsum over the expert dim instead of a
  per-expert loop, so the MXU sees large batched matmuls and the expert dim
  shards over the `ep` mesh axis with plain NamedSharding;
- dispatch is the static-shape capacity algorithm (ops/kernels/moe.py), not
  ragged global_scatter/global_gather CUDA ops;
- expert parallelism = one lax.all_to_all each way inside shard_map.
"""
from __future__ import annotations

import math

import numpy as np
import jax
from .....core.jax_compat import shard_map  # version-adapted (core/jax_compat.py)
from jax.sharding import Mesh, PartitionSpec as P

from .....core.tensor import Tensor, dispatch
from .....nn.layer_base import Layer
from .....nn.initializer import XavierUniform, Normal
from .....ops.kernels.moe import moe_forward_dense, moe_forward_ep
from .gates import BaseGate, GShardGate, SwitchGate, NaiveGate

_GATES = {"gshard": GShardGate, "switch": SwitchGate, "naive": NaiveGate}


class MoELayer(Layer):
    """Token-routed FFN experts with optional expert parallelism.

    Args:
        d_model: hidden size.
        d_ffn: per-expert FFN width.
        num_experts: total expert count E (divisible by ep degree when parallel).
        gate: "gshard" | "switch" | "naive" or a BaseGate instance.
        activation: "swiglu" (llama-style, uses a gate projection) or "gelu".
        mesh / axis_name: expert-parallel mesh axis; None → single-device dense.

    forward(x): x [B, S, D] or [T, D] -> same shape; the load-balancing loss of
    the last call is available as `.l_aux` (add it to the training loss).
    """

    def __init__(self, d_model, d_ffn, num_experts, gate="gshard",
                 activation="swiglu", capacity_factor=None, top_k=None,
                 mesh=None, axis_name="ep", name=None):
        super().__init__()
        if isinstance(gate, str):
            gate_cls = _GATES[gate]
            kwargs = {}
            if capacity_factor is not None:
                kwargs["capacity_factor"] = capacity_factor
            if top_k is not None and gate != "switch":
                kwargs["top_k"] = top_k
            self.gate = gate_cls(d_model, num_experts, **kwargs)
        elif isinstance(gate, BaseGate):
            self.gate = gate
        else:
            raise ValueError(f"gate must be a name or BaseGate, got {gate!r}")
        self.d_model = d_model
        self.d_ffn = d_ffn
        self.num_experts = num_experts
        self.activation = activation
        self.mesh = mesh
        self.axis_name = axis_name
        scale = 1.0 / math.sqrt(d_model)
        init = Normal(std=scale)
        self.w_gate = self.create_parameter((num_experts, d_model, d_ffn),
                                            default_initializer=init)
        self.w_up = self.create_parameter((num_experts, d_model, d_ffn),
                                          default_initializer=init)
        self.w_down = self.create_parameter((num_experts, d_ffn, d_model),
                                            default_initializer=Normal(
                                                std=1.0 / math.sqrt(d_ffn)))
        self.l_aux = None

    def _jax_mesh(self):
        m = self.mesh
        if m is None:
            return None
        return m.jax_mesh() if hasattr(m, "jax_mesh") else m

    def forward(self, x):
        orig_shape = x.shape
        if len(orig_shape) == 3:
            x = x.reshape([-1, orig_shape[-1]])
        cf = self.gate.effective_capacity_factor()
        top_k = self.gate.top_k
        mesh = self._jax_mesh()

        if mesh is None:
            def fn(xv, rw, wg, wu, wd):
                return moe_forward_dense(
                    xv, rw, wg, wu, wd, top_k=top_k, capacity_factor=cf,
                    activation=self.activation)
        else:
            ax = self.axis_name

            def fn(xv, rw, wg, wu, wd):
                f = shard_map(
                    lambda a, b, c, d, e: moe_forward_ep(
                        a, b, c, d, e, ax, top_k=top_k, capacity_factor=cf,
                        activation=self.activation),
                    mesh=mesh,
                    in_specs=(P(ax, None), P(None, None), P(ax, None, None),
                              P(ax, None, None), P(ax, None, None)),
                    out_specs=(P(ax, None), P()))
                return f(xv, rw, wg, wu, wd)

        y, aux = dispatch(fn, (x, self.gate.weight, self.w_gate, self.w_up,
                               self.w_down), {}, name="moe")
        self.l_aux = aux
        if len(orig_shape) == 3:
            y = y.reshape(orig_shape)
        return y
