"""paddle.incubate analog — experimental APIs (fused ops, MoE, …).

Reference: python/paddle/incubate/ (SURVEY.md §2.6: fused NN functionals,
MoE layers, asp sparsity).
"""
from . import nn  # noqa: F401
from . import autograd  # noqa: F401
from . import multiprocessing  # noqa: F401
from . import layers  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .ops import (  # noqa: F401
    softmax_mask_fuse, softmax_mask_fuse_upper_triangle, identity_loss,
    graph_send_recv, graph_reindex, graph_sample_neighbors, graph_khop_sampler,
)
from ..geometric import (  # noqa: F401
    segment_sum, segment_mean, segment_max, segment_min,
)
from ..inference import Config as _InferenceConfig  # noqa: F401
from .. import inference  # noqa: F401
