"""paddle.incubate analog — experimental APIs (fused ops, MoE, …).

Reference: python/paddle/incubate/ (SURVEY.md §2.6: fused NN functionals,
MoE layers, asp sparsity).
"""
from . import nn  # noqa: F401
from . import distributed  # noqa: F401
from . import asp  # noqa: F401
