"""Incubate functional ops: fused softmax-mask, identity_loss, graph_* legacy
aliases.

Reference: python/paddle/incubate/operators/{softmax_mask_fuse.py,
softmax_mask_fuse_upper_triangle.py}, incubate/__init__.py graph_* exports
(the older names for paddle.geometric message passing/sampling ops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import dispatch


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused pass (reference: fused_softmax_mask op;
    XLA fuses the add into the softmax the same way the CUDA kernel does)."""
    return dispatch(
        lambda v, m: jax.nn.softmax(v.astype(jnp.float32) +
                                    m.astype(jnp.float32),
                                    axis=-1).astype(v.dtype),
        (x, mask), {}, name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax (reference: fused_softmax_mask_upper_triangle op):
    entries above the diagonal are masked out."""
    def fn(v):
        sq, sk = v.shape[-2], v.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool))
        logits = jnp.where(cmask, v.astype(jnp.float32), -jnp.inf)
        return jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return dispatch(fn, (x,), {}, name="softmax_mask_fuse_upper_triangle")


def identity_loss(x, reduction="none"):
    """Mark a tensor as a loss and reduce (reference: incubate identity_loss
    op — IPU loss marker; the reduction semantics are what remain here)."""
    if reduction in (0, "sum"):
        return dispatch(lambda v: jnp.sum(v), (x,), {}, name="identity_loss")
    if reduction in (1, "mean"):
        return dispatch(lambda v: jnp.mean(v), (x,), {}, name="identity_loss")
    if reduction in (2, "none"):
        return dispatch(lambda v: v, (x,), {}, name="identity_loss")
    raise ValueError("reduction must be 'sum', 'mean' or 'none'")


# legacy graph_* spellings of the paddle.geometric ops ------------------------

def graph_send_recv(x, src_index, dst_index, pool_type="sum", out_size=None,
                    name=None):
    from ..geometric import send_u_recv
    return send_u_recv(x, src_index, dst_index, reduce_op=pool_type,
                       out_size=out_size)


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    from ..geometric import reindex_graph
    return reindex_graph(x, neighbors, count, value_buffer, index_buffer)


def graph_sample_neighbors(row, colptr, input_nodes, eids=None,
                           perm_buffer=None, sample_size=-1, return_eids=False,
                           flag_perm_buffer=False, name=None):
    from ..geometric import sample_neighbors
    return sample_neighbors(row, colptr, input_nodes, sample_size=sample_size,
                            eids=eids, return_eids=return_eids)


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling (reference: incubate/operators/graph_khop_sampler.py)
    built by iterating sample_neighbors + reindex per hop."""
    import numpy as np
    from ..geometric import sample_neighbors, reindex_graph
    from ..ops.creation import to_tensor
    cur = input_nodes
    all_src, all_dst = [], []
    seen = list(np.asarray(input_nodes._value
                           if hasattr(input_nodes, "_value")
                           else input_nodes).tolist())
    for size in sample_sizes:
        out = sample_neighbors(row, colptr, cur, sample_size=size)
        neigh, cnt = out[0], out[1]
        src, dst, nodes = reindex_graph(cur, neigh, cnt)
        all_src.append(np.asarray(neigh._value))
        all_dst.append(np.repeat(
            np.asarray(cur._value if hasattr(cur, "_value") else cur),
            np.asarray(cnt._value)))
        new = [n for n in np.asarray(neigh._value).tolist() if n not in seen]
        seen.extend(new)
        cur = to_tensor(np.asarray(seen, np.int64))
    edge_src = to_tensor(np.concatenate(all_src) if all_src
                         else np.zeros(0, np.int64))
    edge_dst = to_tensor(np.concatenate(all_dst) if all_dst
                         else np.zeros(0, np.int64))
    sample_index = to_tensor(np.asarray(seen, np.int64))
    reindex = {int(n): i for i, n in enumerate(seen)}
    reindex_arr = to_tensor(np.asarray(
        [reindex[int(v)] for v in np.asarray(edge_src._value).tolist()],
        np.int64))
    return edge_src, edge_dst, sample_index, reindex_arr
