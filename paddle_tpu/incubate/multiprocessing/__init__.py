"""paddle.incubate.multiprocessing — tensor-aware process spawning.

Reference: python/paddle/incubate/multiprocessing/ — a torch-style wrapper
over the stdlib multiprocessing that registers tensor reductions so Tensors
cross process boundaries (CUDA IPC / shared memory file_system in the
reference). TPU-native: device memory is not host-shareable through PJRT, so
tensors serialize by value through shared memory (the reference's
file_system strategy); the DataLoader's high-throughput path uses the native
shm ring in csrc/ instead.
"""
from __future__ import annotations

import multiprocessing
from multiprocessing import *  # noqa: F401,F403

import numpy as np

from ...core.tensor import Tensor


def _reduce_tensor(t):
    return (_rebuild_tensor, (np.asarray(t._value), str(t._value.dtype),
                              t.stop_gradient))


def _rebuild_tensor(arr, dtype, stop_gradient):
    import jax.numpy as jnp
    return Tensor(jnp.asarray(arr), stop_gradient=stop_gradient)


try:
    import multiprocessing.reduction as _reduction
    import copyreg
    copyreg.pickle(Tensor, _reduce_tensor)
except Exception:  # pragma: no cover
    pass


_SHARING_STRATEGY = "file_system"


def set_sharing_strategy(new_strategy):
    global _SHARING_STRATEGY
    if new_strategy not in ("file_system", "file_descriptor"):
        raise ValueError(f"unknown sharing strategy {new_strategy}")
    _SHARING_STRATEGY = new_strategy


def get_sharing_strategy():
    return _SHARING_STRATEGY
