"""paddle.incubate.layers — legacy incubating layer helpers.

Reference: python/paddle/incubate/layers/nn.py (fused_embedding_seq_pool,
shuffle_batch, partial_concat/sum, pow2_decay_with_linear_warmup, ...). The
commonly-used subset is provided; each lowers to existing ops.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ...core import random as _random


def shuffle_batch(x, seed=None):
    """Shuffle rows of a batch (reference: incubate/layers/nn.py
    shuffle_batch). Returns the shuffled tensor (the reference also keeps the
    shuffle order internally for shuffle_batch_grad)."""
    key = _random.next_key() if seed is None else jax.random.PRNGKey(seed)

    def fn(v):
        perm = jax.random.permutation(key, v.shape[0])
        return v[perm]
    return dispatch(fn, (x,), {}, name="shuffle_batch")


def partial_concat(xs, start_index=0, length=-1):
    """Concat column slices of each input (reference: partial_concat op)."""
    def fn(*vals):
        outs = []
        for v in vals:
            end = v.shape[1] if length < 0 else start_index + length
            outs.append(v[:, start_index:end])
        return jnp.concatenate(outs, axis=1)
    return dispatch(fn, tuple(xs), {}, name="partial_concat")


def partial_sum(xs, start_index=0, length=-1):
    def fn(*vals):
        out = 0
        for v in vals:
            end = v.shape[1] if length < 0 else start_index + length
            out = out + v[:, start_index:end]
        return out
    return dispatch(fn, tuple(xs), {}, name="partial_sum")


def pow2_decay_with_linear_warmup(warmup_steps, total_steps, base_lr, end_lr):
    """LR schedule op (reference: pow2_decay_with_linear_warmup): linear
    warmup then (1 - t)^2 decay. Returns a step->lr callable (the eager
    analog of the in-graph counter op)."""
    def lr_at(step):
        step = float(step)
        if step < warmup_steps:
            return base_lr * step / max(warmup_steps, 1)
        t = min(step - warmup_steps, total_steps - warmup_steps)
        frac = 1.0 - t / max(total_steps - warmup_steps, 1)
        return end_lr + (base_lr - end_lr) * frac * frac
    return lr_at


def fused_embedding_seq_pool(input, size, is_sparse=False, padding_idx=None,
                             combiner="sum", param_attr=None, dtype="float32"):
    """Embedding lookup + sequence pool in one op (reference:
    fused_embedding_seq_pool). Padded-dense analog: input (B, T) ids."""
    import paddle_tpu as _paddle
    w = _paddle.create_parameter(list(size), dtype, attr=param_attr)

    def fn(ids, wv):
        emb = wv[ids]
        if padding_idx is not None:
            emb = jnp.where((ids == padding_idx)[..., None], 0.0, emb)
        return emb.sum(axis=1) if combiner == "sum" else emb.mean(axis=1)
    return dispatch(fn, (input, w), {}, name="fused_embedding_seq_pool")
