"""Fused transformer layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward).

On TPU these are compositions XLA fuses; the flash path comes from
scaled_dot_product_attention's Pallas routing."""
from __future__ import annotations

import math

from ...nn.layer_base import Layer
from ...nn.initializer import XavierUniform, Constant
from ...nn import functional as F
from ...nn.functional.attention import scaled_dot_product_attention


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN multi-head self-attention with fused QKV projection."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            (embed_dim, 3 * embed_dim), attr=qkv_weight_attr,
            default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            (3 * embed_dim,), attr=qkv_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr,
            default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=linear_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), attr=ln_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, query, attn_mask=None, cache=None):
        x = query
        if self.normalize_before:
            x = F.layer_norm(x, (self.embed_dim,), self.ln_scale, self.ln_bias,
                             self.epsilon)
        b, s, _ = x.shape
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = query + out
        if not self.normalize_before:
            out = F.layer_norm(out, (self.embed_dim,), self.ln_scale,
                               self.ln_bias, self.epsilon)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr,
            default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), attr=linear1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr,
            default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter(
            (d_model,), attr=linear2_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (d_model,), attr=ln1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, src):
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, (self.d_model,), self.ln_scale, self.ln_bias,
                             self.epsilon)
        h = F.linear(x, self.linear1_weight, self.linear1_bias)
        h = getattr(F, self.activation)(h)
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        h = F.linear(h, self.linear2_weight, self.linear2_bias)
        h = F.dropout(h, self.dropout_rate, training=self.training)
        out = src + h
        if not self.normalize_before:
            out = F.layer_norm(out, (self.d_model,), self.ln_scale, self.ln_bias,
                               self.epsilon)
        return out
