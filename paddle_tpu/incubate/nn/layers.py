"""Fused transformer layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py — FusedMultiHeadAttention, FusedFeedForward).

On TPU these are compositions XLA fuses; the flash path comes from
scaled_dot_product_attention's Pallas routing."""
from __future__ import annotations

import math

from ...nn.layer_base import Layer
from ...nn.initializer import XavierUniform, Constant
from ...nn import functional as F
from ...nn.functional.attention import scaled_dot_product_attention


class FusedMultiHeadAttention(Layer):
    """Pre/post-LN multi-head self-attention with fused QKV projection."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None, normalize_before=False,
                 need_weights=False, qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None, ln_scale_attr=None,
                 ln_bias_attr=None, epsilon=1e-5, nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.qkv_weight = self.create_parameter(
            (embed_dim, 3 * embed_dim), attr=qkv_weight_attr,
            default_initializer=XavierUniform())
        self.qkv_bias = self.create_parameter(
            (3 * embed_dim,), attr=qkv_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr,
            default_initializer=XavierUniform())
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=linear_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), attr=ln_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, query, attn_mask=None, cache=None):
        x = query
        if self.normalize_before:
            x = F.layer_norm(x, (self.embed_dim,), self.ln_scale, self.ln_bias,
                             self.epsilon)
        b, s, _ = x.shape
        qkv = F.linear(x, self.qkv_weight, self.qkv_bias)
        qkv = qkv.reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.attn_dropout_rate,
            training=self.training)
        out = out.reshape([b, s, self.embed_dim])
        out = F.linear(out, self.linear_weight, self.linear_bias)
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = query + out
        if not self.normalize_before:
            out = F.layer_norm(out, (self.embed_dim,), self.ln_scale,
                               self.ln_bias, self.epsilon)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, name=None):
        super().__init__()
        self.d_model = d_model
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.act_dropout_rate = (dropout_rate if act_dropout_rate is None
                                 else act_dropout_rate)
        self.activation = activation
        self.epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr,
            default_initializer=XavierUniform())
        self.linear1_bias = self.create_parameter(
            (dim_feedforward,), attr=linear1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr,
            default_initializer=XavierUniform())
        self.linear2_bias = self.create_parameter(
            (d_model,), attr=linear2_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (d_model,), attr=ln1_bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, src):
        x = src
        if self.normalize_before:
            x = F.layer_norm(x, (self.d_model,), self.ln_scale, self.ln_bias,
                             self.epsilon)
        h = F.linear(x, self.linear1_weight, self.linear1_bias)
        h = getattr(F, self.activation)(h)
        h = F.dropout(h, self.act_dropout_rate, training=self.training)
        h = F.linear(h, self.linear2_weight, self.linear2_bias)
        h = F.dropout(h, self.dropout_rate, training=self.training)
        out = src + h
        if not self.normalize_before:
            out = F.layer_norm(out, (self.d_model,), self.ln_scale, self.ln_bias,
                               self.epsilon)
        return out


class FusedLinear(Layer):
    """reference: incubate/nn/layer/fused_linear.py — linear via the fused
    matmul+bias path (one XLA fusion here)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self.transpose_weight = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(
            shape, attr=weight_attr, default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, input):
        from .functional import fused_linear
        return fused_linear(input, self.weight, self.bias,
                            transpose_weight=self.transpose_weight)


class FusedDropoutAdd(Layer):
    """reference: incubate/nn/layer/fused_dropout_add.py — dropout(x) + y in
    one kernel."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x, y):
        from .functional import fused_dropout_add
        return fused_dropout_add(x, y, p=self.p, training=self.training,
                                 mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}, mode={self.mode}"


class FusedBiasDropoutResidualLayerNorm(Layer):
    """reference: incubate/nn/layer/fused_dropout_nd.py
    FusedBiasDropoutResidualLayerNorm — LN(residual + dropout(x + bias))."""

    def __init__(self, embed_dim, dropout_rate=0.5, weight_attr=None,
                 bias_attr=None, epsilon=1e-5, name=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.dropout_rate = dropout_rate
        self.epsilon = epsilon
        self.linear_bias = self.create_parameter(
            (embed_dim,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=weight_attr, default_initializer=Constant(1.0))
        self.ln_bias = self.create_parameter(
            (embed_dim,), is_bias=True, default_initializer=Constant(0.0))

    def forward(self, x, residual):
        from .functional import fused_bias_dropout_residual_layer_norm
        return fused_bias_dropout_residual_layer_norm(
            x, residual, bias=self.linear_bias, ln_scale=self.ln_scale,
            ln_bias=self.ln_bias, dropout_rate=self.dropout_rate,
            ln_epsilon=self.epsilon, training=self.training)


class FusedTransformerEncoderLayer(Layer):
    """reference: incubate/nn/layer/fused_transformer.py
    FusedTransformerEncoderLayer — FusedMultiHeadAttention + FusedFeedForward."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        attn_dropout_rate = (dropout_rate if attn_dropout_rate is None
                             else attn_dropout_rate)
        act_dropout_rate = (dropout_rate if act_dropout_rate is None
                            else act_dropout_rate)
        self.normalize_before = normalize_before
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=attn_dropout_rate,
            normalize_before=normalize_before,
            qkv_weight_attr=weight_attr, qkv_bias_attr=bias_attr,
            linear_weight_attr=weight_attr, linear_bias_attr=bias_attr)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before,
            linear1_weight_attr=weight_attr, linear1_bias_attr=bias_attr,
            linear2_weight_attr=weight_attr, linear2_bias_attr=bias_attr)

    def forward(self, src, src_mask=None, cache=None):
        out = self.fused_attn(src, attn_mask=src_mask, cache=cache)
        return self.ffn(out)


class FusedMultiTransformer(Layer):
    """Whole-decoder-stack fused transformer for generation (reference:
    incubate/nn/layer/fused_transformer.py FusedMultiTransformer →
    fused_multi_transformer op). num_layers of pre/post-LN attention + FFN
    with optional per-layer KV caches; one module owns every layer's params
    (the weight-list form of the CUDA op)."""

    def __init__(self, embed_dim, num_heads, dim_feedforward,
                 dropout_rate=0.0, activation="gelu", normalize_before=True,
                 ln_scale_attrs=None, ln_bias_attrs=None, qkv_weight_attrs=None,
                 qkv_bias_attrs=None, linear_weight_attrs=None,
                 linear_bias_attrs=None, ffn_ln_scale_attrs=None,
                 ffn_ln_bias_attrs=None, ffn1_weight_attrs=None,
                 ffn1_bias_attrs=None, ffn2_weight_attrs=None,
                 ffn2_bias_attrs=None, epsilon=1e-5, num_layers=-1,
                 nranks=1, trans_qkvw=True, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        if num_layers == -1:
            num_layers = len(qkv_weight_attrs) \
                if isinstance(qkv_weight_attrs, (list, tuple)) else 1
        self.num_layers = num_layers
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout_rate = dropout_rate
        self.activation = activation
        self.normalize_before = normalize_before
        self.epsilon = epsilon
        self.trans_qkvw = trans_qkvw

        def pick(attrs, i):
            return attrs[i] if isinstance(attrs, (list, tuple)) else attrs

        self.ln_scales, self.ln_biases = [], []
        self.qkv_weights, self.qkv_biases = [], []
        self.linear_weights, self.linear_biases = [], []
        self.ffn_ln_scales, self.ffn_ln_biases = [], []
        self.ffn1_weights, self.ffn1_biases = [], []
        self.ffn2_weights, self.ffn2_biases = [], []
        for i in range(num_layers):
            qkv_shape = ((3, num_heads, self.head_dim, embed_dim)
                         if trans_qkvw else
                         (embed_dim, 3, num_heads, self.head_dim))
            add = lambda n, p: (self.add_parameter(f"{n}_{i}", p), p)[1]
            self.ln_scales.append(add("ln_scale", self.create_parameter(
                (embed_dim,), attr=pick(ln_scale_attrs, i),
                default_initializer=Constant(1.0))))
            self.ln_biases.append(add("ln_bias", self.create_parameter(
                (embed_dim,), attr=pick(ln_bias_attrs, i), is_bias=True,
                default_initializer=Constant(0.0))))
            self.qkv_weights.append(add("qkv_weight", self.create_parameter(
                qkv_shape, attr=pick(qkv_weight_attrs, i),
                default_initializer=XavierUniform())))
            self.qkv_biases.append(add("qkv_bias", self.create_parameter(
                (3, num_heads, self.head_dim), attr=pick(qkv_bias_attrs, i),
                is_bias=True, default_initializer=Constant(0.0))))
            self.linear_weights.append(add("linear_weight",
                self.create_parameter(
                    (embed_dim, embed_dim), attr=pick(linear_weight_attrs, i),
                    default_initializer=XavierUniform())))
            self.linear_biases.append(add("linear_bias", self.create_parameter(
                (embed_dim,), attr=pick(linear_bias_attrs, i), is_bias=True,
                default_initializer=Constant(0.0))))
            self.ffn_ln_scales.append(add("ffn_ln_scale",
                self.create_parameter(
                    (embed_dim,), attr=pick(ffn_ln_scale_attrs, i),
                    default_initializer=Constant(1.0))))
            self.ffn_ln_biases.append(add("ffn_ln_bias", self.create_parameter(
                (embed_dim,), attr=pick(ffn_ln_bias_attrs, i), is_bias=True,
                default_initializer=Constant(0.0))))
            self.ffn1_weights.append(add("ffn1_weight", self.create_parameter(
                (embed_dim, dim_feedforward), attr=pick(ffn1_weight_attrs, i),
                default_initializer=XavierUniform())))
            self.ffn1_biases.append(add("ffn1_bias", self.create_parameter(
                (dim_feedforward,), attr=pick(ffn1_bias_attrs, i),
                is_bias=True, default_initializer=Constant(0.0))))
            self.ffn2_weights.append(add("ffn2_weight", self.create_parameter(
                (dim_feedforward, embed_dim), attr=pick(ffn2_weight_attrs, i),
                default_initializer=XavierUniform())))
            self.ffn2_biases.append(add("ffn2_bias", self.create_parameter(
                (embed_dim,), attr=pick(ffn2_bias_attrs, i), is_bias=True,
                default_initializer=Constant(0.0))))

    def forward(self, src, attn_mask=None, caches=None, pre_caches=None,
                rotary_embs=None, rotary_emb_dims=0, seq_lens=None,
                time_step=None):
        from .functional import fused_multi_transformer
        return fused_multi_transformer(
            src, self.ln_scales, self.ln_biases, self.qkv_weights,
            self.qkv_biases, self.linear_weights, self.linear_biases,
            self.ffn_ln_scales, self.ffn_ln_biases, self.ffn1_weights,
            self.ffn1_biases, self.ffn2_weights, self.ffn2_biases,
            pre_layer_norm=self.normalize_before, epsilon=self.epsilon,
            cache_kvs=caches, pre_caches=pre_caches, rotary_embs=rotary_embs,
            rotary_emb_dims=rotary_emb_dims, seq_lens=seq_lens,
            time_step=time_step, attn_mask=attn_mask,
            dropout_rate=self.dropout_rate, activation=self.activation,
            training=self.training, trans_qkvw=self.trans_qkvw)
