from . import functional  # noqa: F401
from .layers import (  # noqa: F401
    FusedMultiHeadAttention, FusedFeedForward, FusedLinear, FusedDropoutAdd,
    FusedBiasDropoutResidualLayerNorm, FusedTransformerEncoderLayer,
    FusedMultiTransformer,
)
