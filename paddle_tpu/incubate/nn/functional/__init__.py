"""paddle.incubate.nn.functional analog — fused NN ops.

Reference: python/paddle/incubate/nn/functional/ (fused_rms_norm.py,
fused_rotary_position_embedding.py, swiglu.py, fused_moe.py,
masked_multihead_attention.py, block_multihead_attention.py,
memory_efficient_attention.py — each a thin wrapper over a fused CUDA kernel).

TPU-native: these are jnp compositions XLA fuses into single kernels on TPU
(rms_norm/rope/swiglu are textbook elementwise-into-matmul fusions); the
attention variants route to the Pallas flash kernel where profitable. The
"fused_" names are kept for API parity.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor, dispatch
from ....core import random as _random
from ....nn.functional.activation import swiglu  # noqa: F401  (parity re-export)
from ....nn.functional.attention import (
    scaled_dot_product_attention, flash_attn_unpadded,
)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None, name=None):
    """RMSNorm with optional pre-norm bias/residual add (reference:
    incubate/nn/functional/fused_rms_norm.py). Returns (out, residual_out) when
    residual is given, else out. Stats in fp32."""
    def fn(xv, *rest):
        i = 0
        w = b = bi = res = None
        if norm_weight is not None:
            w = rest[i]; i += 1
        if norm_bias is not None:
            b = rest[i]; i += 1
        if bias is not None:
            bi = rest[i]; i += 1
        if residual is not None:
            res = rest[i]; i += 1
        if bi is not None:
            xv = xv + bi
        res_out = xv if res is None else xv + res
        x32 = res_out.astype(jnp.float32)
        axis = begin_norm_axis if begin_norm_axis >= 0 else x32.ndim + begin_norm_axis
        dims = tuple(range(axis, x32.ndim))
        var = jnp.mean(jnp.square(x32), axis=dims, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            y = y * w.astype(jnp.float32)
        if b is not None:
            y = y + b.astype(jnp.float32)
        y = y.astype(res_out.dtype)
        return (y, res_out) if res is not None else y

    args = (x,) + tuple(a for a in (norm_weight, norm_bias, bias, residual)
                        if a is not None)
    return dispatch(fn, args, {}, name="fused_rms_norm")


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None, name=None):
    """LayerNorm with optional fused bias/residual add (reference:
    incubate/nn/functional/fused_layer_norm.py)."""
    def fn(xv, *rest):
        i = 0
        w = b = bi = res = None
        if norm_weight is not None:
            w = rest[i]; i += 1
        if norm_bias is not None:
            b = rest[i]; i += 1
        if bias is not None:
            bi = rest[i]; i += 1
        if residual is not None:
            res = rest[i]; i += 1
        if bi is not None:
            xv = xv + bi
        res_out = xv if res is None else xv + res
        x32 = res_out.astype(jnp.float32)
        axis = begin_norm_axis if begin_norm_axis >= 0 else x32.ndim + begin_norm_axis
        dims = tuple(range(axis, x32.ndim))
        mu = jnp.mean(x32, axis=dims, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=dims, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + epsilon)
        if w is not None:
            y = y * w.astype(jnp.float32)
        if b is not None:
            y = y + b.astype(jnp.float32)
        y = y.astype(res_out.dtype)
        return (y, res_out) if res is not None else y

    args = (x,) + tuple(a for a in (norm_weight, norm_bias, bias, residual)
                        if a is not None)
    return dispatch(fn, args, {}, name="fused_layer_norm")


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    name=None):
    """RoPE on [B, S, H, D] tensors (reference:
    incubate/nn/functional/fused_rotary_position_embedding.py).

    sin/cos: [1, S, 1, D] (or [S, D]); computed from rotary_emb_base when absent.
    use_neox_rotary_style=True → rotate-half; False → rotate-every-two (GPT-J).
    """
    have_sincos = sin is not None and cos is not None

    def fn(qv, *rest):
        i = 0
        kv = vv = sn = cs = pid = None
        if k is not None:
            kv = rest[i]; i += 1
        if v is not None:
            vv = rest[i]; i += 1
        if have_sincos:
            sn = rest[i]; cs = rest[i + 1]; i += 2
        if position_ids is not None:
            pid = rest[i]; i += 1
        b, s, h, d = qv.shape
        if sn is None:
            inv = 1.0 / (rotary_emb_base
                         ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
            t = jnp.arange(s, dtype=jnp.float32)
            freqs = jnp.outer(t, inv)                       # [S, D/2]
            emb = (jnp.concatenate([freqs, freqs], -1) if use_neox_rotary_style
                   else jnp.repeat(freqs, 2, -1))
            sn, cs = jnp.sin(emb), jnp.cos(emb)             # [S, D]
        sn = sn.reshape(-1, d).astype(jnp.float32)
        cs = cs.reshape(-1, d).astype(jnp.float32)
        if pid is not None:
            sn = jnp.take(sn, pid, axis=0)                  # [B, S, D]
            cs = jnp.take(cs, pid, axis=0)
            sn = sn[:, :, None, :]
            cs = cs[:, :, None, :]
        else:
            sn = sn[None, :s, None, :]
            cs = cs[None, :s, None, :]

        def rot(x):
            x32 = x.astype(jnp.float32)
            if use_neox_rotary_style:
                half = d // 2
                x1, x2 = x32[..., :half], x32[..., half:]
                rotated = jnp.concatenate([-x2, x1], axis=-1)
            else:
                x1 = x32[..., 0::2]
                x2 = x32[..., 1::2]
                rotated = jnp.stack([-x2, x1], axis=-1).reshape(x32.shape)
            return (x32 * cs + rotated * sn).astype(x.dtype)

        outs = [rot(qv)]
        outs.append(rot(kv) if kv is not None else None)
        outs.append(rot(vv) if vv is not None else None)
        return tuple(outs)

    args = (q,) + tuple(a for a in (k, v) if a is not None)
    if have_sincos:
        args = args + (sin, cos)
    if position_ids is not None:
        args = args + (position_ids,)
    return dispatch(fn, args, {}, name="fused_rope")


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    """x @ W (+ b); reference incubate/nn/functional/fused_matmul_bias.py."""
    def fn(xv, wv, *bv):
        if transpose_weight:
            wv = wv.T
        y = jnp.matmul(xv, wv)
        return y + bv[0] if bv else y
    args = (x, weight) + ((bias,) if bias is not None else ())
    return dispatch(fn, args, {}, name="fused_linear")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """GEMM + bias + activation epilogue (reference fused_gemm_epilogue op)."""
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "none": lambda a: a}[activation]

    def fn(xv, yv, bv):
        if trans_x:
            xv = xv.T
        if trans_y:
            yv = yv.T
        return act(jnp.matmul(xv, yv) + bv)
    return dispatch(fn, (x, y, bias), {}, name="fused_linear_activation")


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, ln_epsilon=1e-5,
                                           training=True, name=None):
    """(x + bias -> dropout) + residual -> LayerNorm (reference fused op)."""
    from ....core import random as _random
    key = _random.next_key() if (dropout_rate > 0.0 and training) else None

    def fn(xv, res, *rest):
        i = 0
        bv = sc = lb = None
        if bias is not None:
            bv = rest[i]; i += 1
        if ln_scale is not None:
            sc = rest[i]; i += 1
        if ln_bias is not None:
            lb = rest[i]; i += 1
        h = xv if bv is None else xv + bv
        if key is not None:
            keep = jax.random.bernoulli(key, 1.0 - dropout_rate, h.shape)
            h = jnp.where(keep, h / (1.0 - dropout_rate), 0.0)
        h = h + res
        x32 = h.astype(jnp.float32)
        mu = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
        y = (x32 - mu) * jax.lax.rsqrt(var + ln_epsilon)
        if sc is not None:
            y = y * sc.astype(jnp.float32)
        if lb is not None:
            y = y + lb.astype(jnp.float32)
        return y.astype(h.dtype)

    args = (x, residual) + tuple(a for a in (bias, ln_scale, ln_bias)
                                 if a is not None)
    return dispatch(fn, args, {}, name="fused_bias_dropout_residual_ln")


def memory_efficient_attention(query, key, value, attn_bias=None, p=0.0,
                               scale=None, training=True, name=None):
    """[B, S, H, D] attention with O(S) memory (reference:
    incubate/nn/functional/memory_efficient_attention.py → xformers kernel).
    On TPU this is the Pallas flash kernel via scaled_dot_product_attention."""
    return scaled_dot_product_attention(query, key, value, attn_mask=attn_bias,
                                        dropout_p=p, is_causal=False,
                                        training=training)


def variable_length_memory_efficient_attention(query, key, value, seq_lens,
                                               kv_seq_lens, mask=None,
                                               scale=None, causal=False,
                                               pre_cache_length=0, name=None):
    """Varlen attention over [B, H, S, D] with per-batch valid lengths."""
    def fn(q, k, v, sl, kl, *m):
        b, h, s, d = q.shape
        sc = scale if scale is not None else 1.0 / math.sqrt(d)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * sc
        q_idx = jnp.arange(s)
        k_idx = jnp.arange(k.shape[2])
        sl = sl.reshape(-1)
        kl = kl.reshape(-1)
        valid = (q_idx[None, :, None] < sl[:, None, None]) & \
                (k_idx[None, None, :] < kl[:, None, None])
        if causal:
            # bottom-right aligned (paddle semantics): query i of the sl valid
            # rows sits at global position offset+i among the kl valid keys,
            # where offset = pre_cache_length (explicit cache) or kl - sl
            off = (jnp.full_like(kl, pre_cache_length) if pre_cache_length > 0
                   else kl - sl)
            valid = valid & (q_idx[None, :, None] + off[:, None, None]
                             >= k_idx[None, None, :])
        logits = jnp.where(valid[:, None], logits, -jnp.inf)
        if m:
            logits = logits + m[0].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(jnp.isnan(probs), 0.0, probs)
        return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(v.dtype), v)
    args = (query, key, value, seq_lens, kv_seq_lens) + \
        ((mask,) if mask is not None else ())
    return dispatch(fn, args, {}, name="varlen_mem_efficient_attention")


def masked_multihead_attention(x, cache_kv, src_mask=None, sequence_lengths=None,
                               rotary_tensor=None, beam_cache_offset=None,
                               qkv_out_scale=None, out_shift=None, out_smooth=None,
                               seq_len=1, rotary_emb_dims=0,
                               use_neox_rotary_style=False, compute_dtype="default",
                               out_scale=-1, quant_round_type=1, quant_max_bound=0,
                               quant_min_bound=0, name=None):
    """Single-token decode attention with an in-place KV cache (reference:
    incubate/nn/functional/masked_multihead_attention.py).

    x: [B, 3*H*D] fused QKV for ONE step; cache_kv: [2, B, H, MaxLen, D];
    sequence_lengths: [B] current lengths (cache write position).
    Returns (out [B, H*D], updated cache_kv) — functional cache update,
    TPU-style, instead of the reference's in-place CUDA write.
    """
    def fn(xv, cache, *rest):
        i = 0
        mask = seqlen = None
        if src_mask is not None:
            mask = rest[i]; i += 1
        if sequence_lengths is not None:
            seqlen = rest[i]; i += 1
        two, b, h, max_len, d = cache.shape
        qkv = xv.reshape(b, 3, h, d)
        q, knew, vnew = qkv[:, 0], qkv[:, 1], qkv[:, 2]    # [B, H, D]
        pos = (seqlen if seqlen is not None
               else jnp.zeros((b,), jnp.int32))             # write index per batch
        onehot = jax.nn.one_hot(pos, max_len, dtype=cache.dtype)  # [B, L]
        kcache = cache[0] * (1 - onehot[:, None, :, None]) + \
            knew[:, :, None, :] * onehot[:, None, :, None]
        vcache = cache[1] * (1 - onehot[:, None, :, None]) + \
            vnew[:, :, None, :] * onehot[:, None, :, None]
        sc = 1.0 / math.sqrt(d)
        logits = jnp.einsum("bhd,bhld->bhl", q, kcache).astype(jnp.float32) * sc
        l_idx = jnp.arange(max_len)
        visible = l_idx[None, :] <= pos[:, None]            # [B, L]
        logits = jnp.where(visible[:, None, :], logits, -jnp.inf)
        if mask is not None:
            logits = logits + mask.reshape(b, 1, -1)[..., :max_len].astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhl,bhld->bhd", probs.astype(vcache.dtype), vcache)
        return out.reshape(b, h * d), jnp.stack([kcache, vcache])

    args = (x, cache_kv) + tuple(a for a in (src_mask, sequence_lengths)
                                 if a is not None)
    return dispatch(fn, args, {}, name="masked_multihead_attention")


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn2_bias=None, quant_method="None", moe_topk=2, norm_topk_prob=True,
              name=None):
    """Dense-device MoE over stacked experts (reference:
    incubate/nn/functional/fused_moe.py). x: [B, S, D] or [T, D];
    ffn1_weight: [E, D, 2F] (swiglu packed) or [E, D, F]; ffn2: [E, F, D]."""
    from ....ops.kernels.moe import top_k_gating

    def fn(xv, gw, w1, w2, *rest):
        i = 0
        b1 = b2 = None
        if ffn1_bias is not None:
            b1 = rest[i]; i += 1
        if ffn2_bias is not None:
            b2 = rest[i]; i += 1
        shp = xv.shape
        xt = xv.reshape(-1, shp[-1])
        t = xt.shape[0]
        e = gw.shape[1]
        # the reference drops nothing (ragged dispatch); at static shapes an
        # ample 2x-expected capacity approximates that while keeping the
        # dispatch buffers O(topk*T*D) instead of O(E*T*D)
        capacity = min(t, 2 * moe_topk * t // e + 8)
        logits = jnp.einsum("td,de->te", xt.astype(jnp.float32),
                            gw.astype(jnp.float32))
        disp, comb, _, _ = top_k_gating(logits, moe_topk, capacity,
                                        norm_topk=norm_topk_prob)
        dispatched = jnp.einsum("tec,td->ecd", disp.astype(xt.dtype), xt)
        h1 = jnp.einsum("ecd,edf->ecf", dispatched, w1)
        if b1 is not None:
            h1 = h1 + b1[:, None, :]
        f2 = w1.shape[-1]
        if w2.shape[1] * 2 == f2:  # packed swiglu [E, D, 2F]
            g, u = jnp.split(h1, 2, -1)
            h = jax.nn.silu(g) * u
        else:
            h = jax.nn.gelu(h1)
        y = jnp.einsum("ecf,efd->ecd", h, w2)
        if b2 is not None:
            y = y + b2[:, None, :]
        out = jnp.einsum("tec,ecd->td", comb.astype(y.dtype), y)
        return out.reshape(shp)

    args = (x, gate_weight, ffn1_weight, ffn2_weight) + tuple(
        a for a in (ffn1_bias, ffn2_bias) if a is not None)
    return dispatch(fn, args, {}, name="fused_moe")


def _dropout_val(v, rate, key, mode):
    """Shared dropout-on-values helper (None key = inference/no-op)."""
    if key is None or rate == 0.0:
        return v
    keep = jax.random.bernoulli(key, 1.0 - rate, v.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, v / (1.0 - rate), 0.0)
    return jnp.where(keep, v, 0.0)


def _layer_norm_val(v, scale, bias, eps):
    """Shared LN-on-values helper; statistics accumulate in fp32 like the
    canonical nn.functional.layer_norm."""
    v32 = v.astype(jnp.float32)
    mu = jnp.mean(v32, -1, keepdims=True)
    var = jnp.var(v32, -1, keepdims=True)
    out = ((v32 - mu) / jnp.sqrt(var + eps)).astype(v.dtype)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


def _add_attn_mask(logits, mask):
    """bool mask = keep-where-True; numeric mask = additive (same convention
    as nn/functional/attention.py)."""
    if mask.dtype == jnp.bool_:
        return jnp.where(mask, logits, jnp.float32(-1e30))
    return logits + mask.astype(jnp.float32)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """dropout(x) + y in one op (reference:
    incubate/nn/functional/fused_dropout_add.py); XLA fuses the mask multiply
    into the add."""
    key = _random.next_key() if training and p > 0.0 else None

    def fn(a, b):
        return _dropout_val(a, p, key, mode) + b

    return dispatch(fn, (x, y), {}, name="fused_dropout_add")


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None, smooth=None,
                   act_method="gelu", compute_dtype="default",
                   quant_scale=-1, quant_round_type=0, quant_max_bound=0,
                   quant_min_bound=0, name=None):
    """bias-add + activation epilogue (reference:
    incubate/nn/functional/fused_bias_act.py). The int8/fp8 quant epilogue
    parameters are not implemented — pass them and you get a loud error, not
    silently-unquantized output."""
    if any(p is not None for p in (dequant_scales, shift, smooth)) \
            or quant_scale != -1:
        raise NotImplementedError(
            "fused_bias_act quantization epilogue (dequant_scales/shift/"
            "smooth/quant_scale) is not implemented; use paddle_tpu.nn.quant "
            "for quantized linears")
    acts = {"gelu": jax.nn.gelu, "relu": jax.nn.relu, "silu": jax.nn.silu,
            "swiglu": None, "geglu": None}
    if act_method not in acts:
        raise ValueError(f"unsupported act_method {act_method!r}")

    def fn(xv, bv):
        if bv is not None:
            xv = xv + bv
        if act_method in ("swiglu", "geglu"):
            a, b = jnp.split(xv, 2, axis=-1)
            gate = jax.nn.silu(a) if act_method == "swiglu" else jax.nn.gelu(a)
            return gate * b
        return acts[act_method](xv)

    return dispatch(fn, (x, bias), {}, name="fused_bias_act")


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu", ln1_epsilon=1e-5,
                      ln2_epsilon=1e-5, pre_layer_norm=False, training=True,
                      mode="upscale_in_train", name=None):
    """Transformer FFN block in one op (reference:
    incubate/nn/functional/fused_transformer.py fused_feedforward):
    residual + LN( x + dropout2( linear2( dropout1( act( linear1(x) ) ) ) ) ),
    with pre-LN variant."""
    act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[activation]
    key1 = _random.next_key() if training and dropout1_rate > 0 else None
    key2 = _random.next_key() if training and dropout2_rate > 0 else None

    def fn(xv, w1, w2, b1, b2, s1, bb1, s2, bb2):
        residual = xv
        h = xv
        if pre_layer_norm:
            h = _layer_norm_val(h, s1, bb1, ln1_epsilon)
        h = jnp.matmul(h, w1)
        if b1 is not None:
            h = h + b1
        h = _dropout_val(act(h), dropout1_rate, key1, mode)
        h = jnp.matmul(h, w2)
        if b2 is not None:
            h = h + b2
        out = residual + _dropout_val(h, dropout2_rate, key2, mode)
        if not pre_layer_norm:
            out = _layer_norm_val(out, s2, bb2, ln2_epsilon)
        return out

    return dispatch(fn, (x, linear1_weight, linear2_weight, linear1_bias,
                         linear2_bias, ln1_scale, ln1_bias, ln2_scale,
                         ln2_bias), {}, name="fused_feedforward")


def fused_multi_head_attention(x, qkv_weight, linear_weight, pre_layer_norm=False,
                               pre_ln_scale=None, pre_ln_bias=None,
                               ln_scale=None, ln_bias=None, pre_ln_epsilon=1e-5,
                               qkv_bias=None, linear_bias=None, cache_kv=None,
                               attn_mask=None, dropout_rate=0.5,
                               attn_dropout_rate=0.5, ln_epsilon=1e-5,
                               training=True, mode="upscale_in_train",
                               ring_id=-1, add_residual=True, name=None):
    """Full MHA block in one op (reference: fused_transformer.py
    fused_multi_head_attention): optional pre-LN, fused QKV GEMM, SDPA,
    out-proj, dropout, residual, post-LN.

    qkv_weight: [3, H, D, hidden]; linear_weight: [hidden, hidden]."""
    key_attn = _random.next_key() if training and attn_dropout_rate > 0 \
        else None
    key_out = _random.next_key() if training and dropout_rate > 0 else None

    def fn(xv, wqkv, wo, pls, plb, lns, lnb, bqkv, bo, mask, cache):
        residual = xv
        h = _layer_norm_val(xv, pls, plb, pre_ln_epsilon) \
            if pre_layer_norm else xv
        three, H, D, hidden = wqkv.shape
        # wqkv [3, H, D, hidden]: contract the hidden dim of the input
        qkv = jnp.einsum("bsx,thdx->tbshd", h, wqkv)
        if bqkv is not None:
            qkv = qkv + bqkv.reshape(3, 1, 1, H, D)
        q, k, v = qkv[0], qkv[1], qkv[2]              # [B, S, H, D]
        new_cache = None
        if cache is not None:
            # cache [2, B, H, T, D]: append this call's K/V (reference
            # returns cache_kv_out alongside out)
            k_hist = jnp.moveaxis(cache[0], 2, 1)     # [B, T, H, D]
            v_hist = jnp.moveaxis(cache[1], 2, 1)
            k = jnp.concatenate([k_hist, k], axis=1)
            v = jnp.concatenate([v_hist, v], axis=1)
            new_cache = jnp.stack([jnp.moveaxis(k, 1, 2),
                                   jnp.moveaxis(v, 1, 2)])
        sc = 1.0 / math.sqrt(D)
        logits = jnp.einsum("bshd,bthd->bhst", q, k).astype(jnp.float32) * sc
        if mask is not None:
            logits = _add_attn_mask(logits, mask)
        probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
        probs = _dropout_val(probs, attn_dropout_rate, key_attn, mode)
        ctx = jnp.einsum("bhst,bthd->bshd", probs, v)
        ctx = ctx.reshape(ctx.shape[0], ctx.shape[1], H * D)
        out = jnp.matmul(ctx, wo)
        if bo is not None:
            out = out + bo
        out = _dropout_val(out, dropout_rate, key_out, mode)
        if add_residual:
            out = residual + out
        if not pre_layer_norm:
            out = _layer_norm_val(out, lns, lnb, ln_epsilon)
        if cache is not None:
            return out, new_cache
        return out

    return dispatch(fn, (x, qkv_weight, linear_weight, pre_ln_scale,
                         pre_ln_bias, ln_scale, ln_bias, qkv_bias, linear_bias,
                         attn_mask, cache_kv), {},
                    name="fused_multi_head_attention")


def _kv_quant_scatter(pool, scales, wblk, slot, rows, quant, D,
                      end_rows):
    """Merge new token rows into a QUANTIZED block pool — the dense
    fallback's write rule, shared by the decode and append forms: the
    affected blocks dequantize, take the new rows, ZERO their dead tail
    (rows at or past ``end_rows`` — stale content of a reused freed
    block; attention always masks those positions, but an unmasked
    absmax would let a dirty block's garbage inflate the scale and
    crush the live rows' resolution), recompute their per-(block, head)
    absmax scale, and re-quantize; every untouched block keeps its
    exact int payload and scale (no silent re-rounding of blocks
    nothing wrote). ``wblk``/``slot``/``rows``/``end_rows`` are flat
    write coordinates (block index ``pool.shape[0]`` = out-of-range
    drop, the decode form's -1-table contract; ``end_rows[i]`` = live
    row COUNT of block ``wblk[i]`` after this write). O(pool) compute —
    acceptable on the CPU/tier-1 path this fallback serves; the TPU
    path is the in-VMEM Pallas variant.

    Returns ``(pool, scales)`` updated."""
    from ....ops.kernels.paged_attention import (
        kv_block_scale, kv_quantize, kv_unpack)

    nb, _, bs, _ = pool.shape
    written = jnp.zeros((nb + 1,), bool).at[wblk].set(True)[:nb]
    live_end = jnp.full((nb + 1,), bs, jnp.int32) \
        .at[wblk].set(end_rows.astype(jnp.int32), mode="drop")[:nb]
    pf = kv_unpack(pool, quant, D) * scales[..., None, None]
    pf = pf.at[wblk, :, slot].set(rows.astype(jnp.float32), mode="drop")
    dead = jnp.arange(bs)[None, None, :] >= live_end[:, None, None]
    pf = jnp.where(dead[..., None], jnp.float32(0.0), pf)
    new_s = kv_block_scale(pf, quant, axes=(2, 3))        # [NB, Hkv]
    pq = kv_quantize(pf, new_s[..., None, None], quant)
    pool = jnp.where(written[:, None, None, None], pq, pool)
    scales = jnp.where(written[:, None], new_s, scales)
    return pool, scales


def _kv_quant_gather(pool, scales, safe_tables, quant, D):
    """Per-sequence logical KV off a QUANTIZED pool: gather the table's
    blocks, dequantize with their per-(block, head) scales -> f32
    [B, MB, Hkv, bs, D] for the dense attention math."""
    from ....ops.kernels.paged_attention import kv_unpack
    return kv_unpack(pool[safe_tables], quant, D) * \
        scales[safe_tables][..., None, None]


def block_multihead_attention(qkv, key_cache, value_cache, seq_lens_encoder,
                              seq_lens_decoder, seq_lens_this_time,
                              padding_offsets=None, cum_offsets=None,
                              cu_seqlens_q=None, cu_seqlens_k=None,
                              block_tables=None, pre_key_cache=None,
                              pre_value_cache=None, cache_k_quant_scales=None,
                              cache_v_quant_scales=None, max_seq_len=None,
                              block_size=None, use_neox_style=False,
                              cache_quant_type=None, name=None):
    """Paged-KV-cache decode attention (reference:
    incubate/nn/functional/block_multihead_attention.py, phi
    block_multi_head_attention_kernel.cu — the vLLM-style paged attention).

    Decode-step form: qkv [B, (Hq + 2*Hkv)*D] (one new token per sequence;
    Hq == Hkv is the MHA special case, Hq a multiple of Hkv is GQA);
    key_cache/value_cache [num_blocks, Hkv, block_size, D]; block_tables
    [B, max_blocks_per_seq] maps logical KV block i of each sequence to a
    physical cache block (-1 = unused); seq_lens_decoder [B] = tokens already
    cached. Returns (out [B, Hq*D], key_cache, value_cache) with the new
    token written into its block — functional cache update, TPU-style.

    On TPU (and unless FLAGS_use_paged_attention=0) this routes through the
    Pallas paged-attention decode kernel
    (:func:`paddle_tpu.ops.kernels.paged_attention.paged_attention_decode`):
    block-sparse reads straight off the physical pools via scalar-prefetched
    block tables, with the new-token write fused in-kernel. The dense path
    below (scatter + gather the whole padded horizon + einsum) is the
    reference semantics and the CPU/tier-1 fallback.

    Append-step form (the fused prefill+decode scheduler's mixed step):
    qkv [B, S, (Hq + 2*Hkv)*D] with ``seq_lens_this_time`` [B] = how many
    of the S rows are real for each sequence (0 = inactive slot). Sequence
    b's rows occupy positions [seq_lens_decoder[b], seq_lens_decoder[b] +
    seq_lens_this_time[b]); each row attends causally to the pooled
    history plus its own chunk prefix. Rows past seq_lens_this_time are
    padding: nothing is written for them and their outputs are garbage
    the caller ignores. Routes through
    :func:`~paddle_tpu.ops.kernels.paged_attention.paged_attention_append`
    on TPU; the dense scatter+gather+einsum below is the CPU fallback.

    Quantized pools (``cache_quant_type="int8"|"int4"`` — the serving
    engine's ``kv_cache_dtype``; the reference signature's
    ``cache_k_quant_scales``/``cache_v_quant_scales`` carry the
    per-(physical block, kv head) fp32 scale arrays [num_blocks, Hkv]):
    both forms dequantize blocks on read and re-quantize every written
    block with a fresh absmax scale, returning the updated scale arrays
    after the pools — ``(out, key_cache, value_cache, k_scales,
    v_scales)``. On TPU the dequant/requant happens in VMEM inside the
    Pallas kernels; the dense fallback below does the same math at the
    XLA level (host-runnable, the tier-1 path). int4 packs two nibbles
    per pool byte along D (split-half layout, even head_dim here — the
    kernel itself also supports odd D with nibble padding).
    """
    if block_tables is None:
        raise ValueError("block_mha requires block_tables")
    quant = cache_quant_type
    if quant and (cache_k_quant_scales is None
                  or cache_v_quant_scales is None):
        raise ValueError("cache_quant_type needs cache_k_quant_scales and "
                         "cache_v_quant_scales ([num_blocks, Hkv] fp32)")
    if len(qkv.shape) == 3:
        if seq_lens_this_time is None:
            raise ValueError("append-step block_mha (3-D qkv) requires "
                             "seq_lens_this_time (per-sequence q_lens)")
        return _block_mha_append(qkv, key_cache, value_cache,
                                 seq_lens_decoder, seq_lens_this_time,
                                 block_tables, cache_k_quant_scales,
                                 cache_v_quant_scales, quant)
    def fn(qkv_v, kc, vc, lens, tables, *qargs):
        from ....ops.kernels.paged_attention import (
            current_paged_tp, paged_attention_decode,
            paged_attention_decode_tp, paged_attention_enabled)

        nb, Hkv, bs, Dp = kc.shape
        b = qkv_v.shape[0]
        max_blocks = tables.shape[1]
        if quant:
            ks, vs = (a.astype(jnp.float32) for a in qargs)
            D = _quant_head_dim(qkv_v.shape[1], Hkv, Dp, quant)
        else:
            ks = vs = None
            D = Dp
        Hq = qkv_v.shape[1] // D - 2 * Hkv
        q = qkv_v[:, :Hq * D].reshape(b, Hq, D)
        knew = qkv_v[:, Hq * D:(Hq + Hkv) * D].reshape(b, Hkv, D)
        vnew = qkv_v[:, (Hq + Hkv) * D:].reshape(b, Hkv, D)
        lens = lens.astype(jnp.int32)
        tables = tables.astype(jnp.int32)

        if paged_attention_enabled():
            tp = current_paged_tp()
            if tp is not None:
                # TP serving engine: a pallas_call cannot be GSPMD-
                # partitioned, so the kernel shard_maps over the tp axis
                # (kv-head shards; tables/lens/scales replicated along
                # their non-head dims)
                outs = paged_attention_decode_tp(
                    q, kc, vc, tables, lens, mesh=tp[0], axis=tp[1],
                    new_k=knew, new_v=vnew, k_scale=ks, v_scale=vs,
                    quant=quant)
            else:
                outs = paged_attention_decode(
                    q, kc, vc, tables, lens, new_k=knew, new_v=vnew,
                    k_scale=ks, v_scale=vs, quant=quant)
            if quant:
                out, kc, vc, ks, vs = outs
                return out.reshape(b, Hq * D), kc, vc, ks, vs
            out, kc, vc = outs
            return out.reshape(b, Hq * D), kc, vc

        # write the new token at position lens[i] of sequence i. A -1 table
        # entry (no block allocated) must not write AT ALL: clamping it to
        # block 0 and re-writing the old value is NOT a no-op when another
        # sequence genuinely writes block 0 in the same scatter — duplicate
        # indices make the last write win, clobbering the real token with
        # the stale value. Route invalid rows OUT OF BOUNDS and drop them.
        blk_idx = tables[jnp.arange(b), lens // bs]       # [B] physical block
        slot = lens % bs                                  # [B]
        wblk = jnp.where(blk_idx >= 0, blk_idx, nb)       # nb = out of range
        if quant:
            # quantized merge: dead tail past the new token zeroed,
            # fresh absmax scale per written block
            kc, ks = _kv_quant_scatter(kc, ks, wblk, slot, knew, quant,
                                       D, slot + 1)
            vc, vs = _kv_quant_scatter(vc, vs, wblk, slot, vnew, quant,
                                       D, slot + 1)
        else:
            kc = kc.at[wblk, :, slot].set(knew, mode="drop")
            vc = vc.at[wblk, :, slot].set(vnew, mode="drop")

        # gather each sequence's logical KV [B, max_blocks*bs, Hkv, D]
        safe_tables = jnp.maximum(tables, 0)
        if quant:
            kseq = _kv_quant_gather(kc, ks, safe_tables, quant, D)
            vseq = _kv_quant_gather(vc, vs, safe_tables, quant, D)
        else:
            kseq = kc[safe_tables]                        # [B, MB, Hkv, bs, D]
            vseq = vc[safe_tables]
        kseq = jnp.moveaxis(kseq, 3, 2).reshape(b, max_blocks * bs, Hkv, D)
        vseq = jnp.moveaxis(vseq, 3, 2).reshape(b, max_blocks * bs, Hkv, D)

        sc = 1.0 / math.sqrt(D)
        qg = q.reshape(b, Hkv, Hq // Hkv, D)              # GQA head groups
        logits = jnp.einsum("bhgd,bthd->bhgt", qg,
                            kseq).astype(jnp.float32) * sc
        t_idx = jnp.arange(max_blocks * bs)
        visible = t_idx[None, :] <= lens[:, None]         # include new token
        logits = jnp.where(visible[:, None, None, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1).astype(vseq.dtype)
        out = jnp.einsum("bhgt,bthd->bhgd", probs, vseq)
        if quant:
            return (out.astype(qkv_v.dtype).reshape(b, Hq * D),
                    kc, vc, ks, vs)
        return out.reshape(b, Hq * D), kc, vc

    args = (qkv, key_cache, value_cache, seq_lens_decoder, block_tables)
    if quant:
        args += (cache_k_quant_scales, cache_v_quant_scales)
        return dispatch(fn, args, {}, name="block_mha_decode_quant")
    return dispatch(fn, args, {}, name="block_multihead_attention")


def _quant_head_dim(qkv_width, Hkv, Dp, quant):
    """Head dim D of a quantized-pool call, from the qkv row width and
    the PACKED pool head dim Dp. int8 stores D bytes (D == Dp); int4
    packs two per byte, so D is 2*Dp — or 2*Dp - 1 for an odd head dim,
    disambiguated by which one divides the qkv width into a whole
    (GQA-consistent) head count. Odd-D models this can't disambiguate
    should call the Pallas kernel directly (serving models have even
    head dims)."""
    if quant == "int8":
        return Dp
    D = 2 * Dp
    if qkv_width % D == 0 and (qkv_width // D - 2 * Hkv) > 0 \
            and (qkv_width // D - 2 * Hkv) % Hkv == 0:
        return D
    return D - 1


def _block_mha_append(qkv, key_cache, value_cache, seq_lens, q_lens,
                      block_tables, k_scales=None, v_scales=None,
                      quant=None):
    """Append-step paged attention (see block_multihead_attention): S new
    positions per sequence against the block pools, causal within the
    chunk. Dense fallback = scatter the valid rows into their blocks
    (invalid rows route out of range and drop), gather each sequence's
    padded horizon, einsum with the per-row causal mask — the same
    reference semantics the decode form uses, extended along S.
    ``quant`` + scale arrays: quantized pools (dequant-on-read, window
    blocks re-quantized under fresh absmax scales; return grows the
    updated scale arrays)."""
    def fn(qkv_v, kc, vc, lens, qlens, tables, *qargs):
        from ....ops.kernels.paged_attention import (
            current_paged_tp, paged_attention_append,
            paged_attention_append_tp, paged_attention_enabled)

        nb, Hkv, bs, Dp = kc.shape
        b, S = qkv_v.shape[0], qkv_v.shape[1]
        max_blocks = tables.shape[1]
        if quant:
            ks, vs = (a.astype(jnp.float32) for a in qargs)
            D = _quant_head_dim(qkv_v.shape[2], Hkv, Dp, quant)
        else:
            ks = vs = None
            D = Dp
        Hq = qkv_v.shape[2] // D - 2 * Hkv
        q = qkv_v[:, :, :Hq * D].reshape(b, S, Hq, D)
        knew = qkv_v[:, :, Hq * D:(Hq + Hkv) * D].reshape(b, S, Hkv, D)
        vnew = qkv_v[:, :, (Hq + Hkv) * D:].reshape(b, S, Hkv, D)
        lens = lens.astype(jnp.int32)
        qlens = qlens.astype(jnp.int32)
        tables = tables.astype(jnp.int32)

        if paged_attention_enabled():
            tp = current_paged_tp()
            if tp is not None:
                outs = paged_attention_append_tp(
                    q, kc, vc, tables, lens, qlens, knew, vnew,
                    mesh=tp[0], axis=tp[1], k_scale=ks, v_scale=vs,
                    quant=quant)
            else:
                outs = paged_attention_append(
                    q, kc, vc, tables, lens, qlens, knew, vnew,
                    k_scale=ks, v_scale=vs, quant=quant)
            if quant:
                out, kc, vc, ks, vs = outs
                return out.reshape(b, S, Hq * D), kc, vc, ks, vs
            out, kc, vc = outs
            return out.reshape(b, S, Hq * D), kc, vc

        # scatter valid rows: row i of sequence b lands at absolute
        # position lens[b]+i when i < qlens[b]; padding / unallocated /
        # out-of-table rows route out of range and DROP (same contract as
        # the decode form — a clamped write could clobber a real block)
        i_idx = jnp.arange(S, dtype=jnp.int32)
        pos = lens[:, None] + i_idx[None, :]                  # [B, S]
        valid = i_idx[None, :] < qlens[:, None]
        blk_log = pos // bs
        phys = jnp.take_along_axis(
            tables, jnp.clip(blk_log, 0, max_blocks - 1), axis=1)
        wblk = jnp.where(valid & (phys >= 0) & (blk_log < max_blocks),
                         phys, nb)                            # nb = OOB
        slot = pos % bs
        wf, sf = wblk.reshape(-1), slot.reshape(-1)
        if quant:
            # live row count of each written block: the window's new end
            # (lens + q_lens) relative to the block start, clipped
            ends = jnp.clip((lens + qlens)[:, None] - blk_log * bs, 0, bs)
            ef = ends.reshape(-1)
            kc, ks = _kv_quant_scatter(kc, ks, wf, sf,
                                       knew.reshape(-1, Hkv, D), quant, D,
                                       ef)
            vc, vs = _kv_quant_scatter(vc, vs, wf, sf,
                                       vnew.reshape(-1, Hkv, D), quant, D,
                                       ef)
        else:
            kc = kc.at[wf, :, sf].set(knew.reshape(-1, Hkv, D),
                                      mode="drop")
            vc = vc.at[wf, :, sf].set(vnew.reshape(-1, Hkv, D),
                                      mode="drop")

        # gather each sequence's logical KV and attend with the per-row
        # causal mask: kv position t visible to chunk row i iff
        # t <= lens + i
        safe_tables = jnp.maximum(tables, 0)
        if quant:
            kseq = _kv_quant_gather(kc, ks, safe_tables, quant, D)
            vseq = _kv_quant_gather(vc, vs, safe_tables, quant, D)
        else:
            kseq = kc[safe_tables]                   # [B, MB, Hkv, bs, D]
            vseq = vc[safe_tables]
        kseq = jnp.moveaxis(kseq, 3, 2).reshape(b, max_blocks * bs, Hkv, D)
        vseq = jnp.moveaxis(vseq, 3, 2).reshape(b, max_blocks * bs, Hkv, D)
        sc = 1.0 / math.sqrt(D)
        qg = q.reshape(b, S, Hkv, Hq // Hkv, D)      # GQA head groups
        logits = jnp.einsum("bshgd,bthd->bhsgt", qg,
                            kseq).astype(jnp.float32) * sc
        t_idx = jnp.arange(max_blocks * bs)
        visible = t_idx[None, None, :] <= (lens[:, None]
                                           + i_idx[None, :])[:, :, None]
        logits = jnp.where(visible[:, None, :, None, :], logits, -jnp.inf)
        probs = jax.nn.softmax(logits, axis=-1).astype(vseq.dtype)
        out = jnp.einsum("bhsgt,bthd->bshgd", probs, vseq)
        if quant:
            return (out.astype(qkv_v.dtype).reshape(b, S, Hq * D),
                    kc, vc, ks, vs)
        return out.reshape(b, S, Hq * D), kc, vc

    args = (qkv, key_cache, value_cache, seq_lens, q_lens, block_tables)
    if quant:
        args += (k_scales, v_scales)
        return dispatch(fn, args, {}, name="block_mha_append_quant")
    return dispatch(fn, args, {}, name="block_mha_append")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """reference: incubate/nn/functional/fused_matmul_bias.py — one
    GEMM+bias-epilogue (XLA fuses the add into the dot)."""
    def fn(a, b, *bi):
        aa = jnp.swapaxes(a, -2, -1) if transpose_x else a
        bb = jnp.swapaxes(b, -2, -1) if transpose_y else b
        out = aa @ bb
        if bi:
            out = out + bi[0]
        return out
    args = (x, y) + ((bias,) if bias is not None else ())
    return dispatch(fn, args, {}, name="fused_matmul_bias")


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """reference: incubate/nn/functional/blha_get_max_len.py — max
    encoder/decoder sequence lengths for block_multihead_attention setup."""
    def fn(enc, dec):
        return jnp.max(enc).reshape([1]), jnp.max(dec).reshape([1])
    return dispatch(fn, (seq_lens_encoder, seq_lens_decoder), {},
                    name="blha_get_max_len")


def fused_multi_transformer(x, ln_scales, ln_biases, qkv_weights, qkv_biases,
                            linear_weights, linear_biases, ffn_ln_scales,
                            ffn_ln_biases, ffn1_weights, ffn1_biases,
                            ffn2_weights, ffn2_biases, pre_layer_norm=True,
                            epsilon=1e-5, cache_kvs=None, pre_caches=None,
                            rotary_embs=None, rotary_emb_dims=0, beam_offset=None,
                            seq_lens=None, time_step=None, attn_mask=None,
                            dropout_rate=0.0, activation="gelu", training=False,
                            mode="upscale_in_train", trans_qkvw=True,
                            ring_id=-1, name=None):
    """Whole-stack fused transformer (reference:
    incubate/nn/functional/fused_multi_transformer.py — the generation-path
    mega-op). Loops the per-layer fused blocks; each block is one XLA fusion
    region; KV caches append along seq when cache_kvs is given (decode step).

    Returns output, or (output, cache_kvs) when cache_kvs is not None."""
    from ....nn import functional as NF
    from ....nn.functional.attention import scaled_dot_product_attention

    num_layers = len(qkv_weights)
    out = x
    new_caches = []
    for i in range(num_layers):
        residual = out
        h = out
        if pre_layer_norm:
            h = NF.layer_norm(h, (h.shape[-1],), ln_scales[i], ln_biases[i],
                              epsilon)
        b, s, d = h.shape
        qkv_w = qkv_weights[i]
        if trans_qkvw:
            # (3, H, Dh, D) -> project: x @ W^T per slot
            def qkv_fn(hv, wv, bv):
                out3 = jnp.einsum("bsd,thkd->bsthk", hv, wv)
                return out3 + bv[None, None]
            qkv = dispatch(qkv_fn, (h, qkv_w, qkv_biases[i]), {},
                           name="fmt_qkv")
        else:
            def qkv_fn(hv, wv, bv):
                out3 = jnp.einsum("bsd,dthk->bsthk", hv, wv)
                return out3 + bv[None, None]
            qkv = dispatch(qkv_fn, (h, qkv_w, qkv_biases[i]), {},
                           name="fmt_qkv")
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        if rotary_embs is not None and rotary_emb_dims > 0:
            q, k, _ = fused_rotary_position_embedding(
                q, k, sin=rotary_embs[0], cos=rotary_embs[1])
        if cache_kvs is not None and cache_kvs[i] is not None:
            cache = cache_kvs[i]  # (2, B, H, S_cache, Dh) paddle layout
            def append_fn(cv, kv, vv):
                kq = jnp.swapaxes(kv, 1, 2)  # B,H,S,Dh
                vq = jnp.swapaxes(vv, 1, 2)
                nk = jnp.concatenate([cv[0], kq], axis=2)
                nv = jnp.concatenate([cv[1], vq], axis=2)
                return jnp.stack([nk, nv])
            new_cache = dispatch(append_fn, (cache, k, v), {},
                                 name="fmt_cache_append")
            new_caches.append(new_cache)
            def split_fn(cv):
                return (jnp.swapaxes(cv[0], 1, 2), jnp.swapaxes(cv[1], 1, 2))
            k, v = dispatch(split_fn, (new_cache,), {}, name="fmt_cache_read")
        attn = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            is_causal=(attn_mask is None and cache_kvs is None),
            dropout_p=0.0, training=training)
        attn = attn.reshape([b, s, d])
        attn = NF.linear(attn, linear_weights[i], linear_biases[i])
        if dropout_rate and training:
            attn = NF.dropout(attn, dropout_rate, training=training)
        out = residual + attn
        if not pre_layer_norm:
            out = NF.layer_norm(out, (d,), ln_scales[i], ln_biases[i], epsilon)

        residual = out
        h = out
        if pre_layer_norm:
            h = NF.layer_norm(h, (d,), ffn_ln_scales[i], ffn_ln_biases[i],
                              epsilon)
        h = NF.linear(h, ffn1_weights[i], ffn1_biases[i])
        h = getattr(NF, activation)(h)
        if dropout_rate and training:
            h = NF.dropout(h, dropout_rate, training=training)
        h = NF.linear(h, ffn2_weights[i], ffn2_biases[i])
        out = residual + h
        if not pre_layer_norm:
            out = NF.layer_norm(out, (d,), ffn_ln_scales[i], ffn_ln_biases[i],
                                epsilon)
    if cache_kvs is not None:
        return out, new_caches
    return out
