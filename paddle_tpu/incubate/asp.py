"""paddle.incubate.asp analog — Automatic SParsity (2:4 structured pruning).

Reference: python/paddle/incubate/asp/ (decorate wraps the optimizer so masks
re-apply after each step; prune_model computes n:m masks per supported layer;
check_sparsity validates). TPU-native: masks are plain multiplicative buffers
applied to weight values — XLA folds the elementwise mask into the consumer
matmul; there's no sparse-tensor-core path to target, so the win is model
compression/regularization parity with the reference API.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..nn.layer_base import Layer

__all__ = ["decorate", "prune_model", "check_sparsity", "reset_excluded_layers",
           "set_excluded_layers"]

_excluded: set[str] = set()
_masks: dict[int, np.ndarray] = {}


def set_excluded_layers(layer_names, main_program=None):
    for n in (layer_names if isinstance(layer_names, (list, tuple))
              else [layer_names]):
        _excluded.add(n)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _nm_mask_2d(w, n=2, m=4):
    """Keep the n largest-magnitude entries of every m along the input dim."""
    rows, cols = w.shape
    pad = (-cols) % m
    wp = np.pad(np.abs(w), ((0, 0), (0, pad)))
    groups = wp.reshape(rows, -1, m)
    order = np.argsort(-groups, axis=-1)
    mask = np.zeros_like(groups, dtype=bool)
    np.put_along_axis(mask, order[..., :n], True, axis=-1)
    mask = mask.reshape(rows, -1)[:, :cols]
    return mask


def _supported(layer):
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D
    return isinstance(layer, (Linear, Conv2D))


def _iter_prunable(model):
    for name, sub in model.named_sublayers():
        if name in _excluded or not _supported(sub):
            continue
        yield name, sub


def _to_out_in(w):
    """View the weight as (out, in*): Linear stores (in, out) → transpose;
    Conv stores (out, in/g, kh, kw) → flatten trailing dims."""
    if w.ndim == 2:
        return w.T, "T"
    return w.reshape(w.shape[0], -1), "flat"


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    """Compute and apply n:m masks (groups run along the INPUT dim) to every
    supported layer's weight (reference: asp/asp.py prune_model)."""
    pruned = {}
    for name, sub in _iter_prunable(model):
        w = sub.weight.numpy()
        w2, kind = _to_out_in(w)
        mask2 = _nm_mask_2d(w2, n, m)
        mask = mask2.T if kind == "T" else mask2.reshape(w.shape)
        sub.weight._value = np.asarray(w * mask, dtype=w.dtype)
        if with_mask:
            import weakref
            # weakref guards against id() reuse after GC; re-pruning must also
            # drop the stale device-side copy
            _masks[id(sub.weight)] = (mask, weakref.ref(sub.weight))
            _masks.pop(("dev", id(sub.weight)), None)
        pruned[name] = mask
    return pruned


def check_sparsity(weight, n=2, m=4):
    """True iff every m-group along the input dim has ≤ n nonzeros."""
    w = weight.numpy() if isinstance(weight, Tensor) else np.asarray(weight)
    w2, _ = _to_out_in(w)
    rows, cols = w2.shape
    pad = (-cols) % m
    wp = np.pad(w2 != 0, ((0, 0), (0, pad)))
    return bool((wp.reshape(rows, -1, m).sum(-1) <= n).all())


class _MaskedOptimizer:
    """Wraps an optimizer so the sparsity masks re-apply after each step
    (the reference's OptimizerWithSparsityGuarantee)."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        from ..core.tensor import dispatch, no_grad
        import jax.numpy as jnp
        with no_grad():
            for p in self._inner._parameter_list:
                entry = _masks.get(id(p))
                if entry is None:
                    continue
                mask, ref = entry
                if ref() is not p:  # id() reuse after GC — not our parameter
                    continue
                # on-device multiply: the mask uploads once and XLA folds the
                # product into the next consumer; no per-step host round trip
                dev_key = ("dev", id(p))
                if dev_key not in _masks:
                    _masks[dev_key] = jnp.asarray(
                        mask, dtype=jnp.asarray(p._value).dtype)
                dmask = _masks[dev_key]
                masked = dispatch(lambda v: v * dmask, (p,), {},
                                  name="asp_mask")
                p._value = masked._value


def decorate(optimizer):
    """Reference: asp/asp.py decorate."""
    return _MaskedOptimizer(optimizer)
