"""paddle.incubate.autograd — functional autodiff (vjp/jvp/Jacobian/Hessian)
and the prim-mode switches.

Reference: python/paddle/incubate/autograd/ (primapi.py forward_grad/grad,
functional.py vjp/jvp/Jacobian/Hessian, primx "prim" op decomposition).
TPU-native: jax IS the primitive system — vjp/jvp map to jax.vjp/jax.jvp over
the op library; enable/disable_prim toggle a flag only (every op is already
expressed in primitives XLA understands).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...autograd import jacobian as _jacobian, hessian as _hessian

__all__ = ['vjp', 'jvp', 'Jacobian', 'Hessian', 'enable_prim', 'disable_prim',
           'forward_grad', 'grad']

_PRIM_ENABLED = False


def enable_prim():
    """reference: primapi — turn on primitive-op decomposition. XLA always
    runs on primitives; the flag is tracked for prim_enabled() parity."""
    global _PRIM_ENABLED
    _PRIM_ENABLED = True


def disable_prim():
    global _PRIM_ENABLED
    _PRIM_ENABLED = False


def prim_enabled():
    return _PRIM_ENABLED


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def _pure(func):
    def fn(*vals):
        out = func(*[Tensor(v) for v in vals])
        if isinstance(out, (list, tuple)):
            return tuple(o._value for o in out)
        return out._value
    return fn


def vjp(func, xs, v=None):
    """reference: incubate/autograd/functional.py vjp — returns
    (func(xs), vjp_result)."""
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    vals = [_unwrap(x) for x in xs_l]
    out, vjp_fn = jax.vjp(_pure(func), *vals)
    if v is None:
        if isinstance(out, tuple):
            cot = tuple(jnp.ones_like(o) for o in out)
        else:
            cot = jnp.ones_like(out)
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        cot = tuple(_unwrap(x) for x in v_l)
        if not isinstance(out, tuple):
            cot = cot[0]
    grads = vjp_fn(cot)
    outs = (tuple(Tensor(o) for o in out) if isinstance(out, tuple)
            else Tensor(out))
    gs = [Tensor(g) for g in grads]
    return outs, (gs if len(gs) > 1 else gs[0])


def jvp(func, xs, v=None):
    """reference: functional.py jvp — returns (func(xs), jvp_result)."""
    xs_l = xs if isinstance(xs, (list, tuple)) else [xs]
    vals = [_unwrap(x) for x in xs_l]
    if v is None:
        tangents = tuple(jnp.ones_like(x) for x in vals)
    else:
        v_l = v if isinstance(v, (list, tuple)) else [v]
        tangents = tuple(_unwrap(x) for x in v_l)
    out, tangent_out = jax.jvp(_pure(func), tuple(vals), tangents)
    outs = (tuple(Tensor(o) for o in out) if isinstance(out, tuple)
            else Tensor(out))
    touts = (tuple(Tensor(t) for t in tangent_out)
             if isinstance(tangent_out, tuple) else Tensor(tangent_out))
    return outs, touts


forward_grad = jvp  # primapi.forward_grad: forward-mode grads
grad = vjp          # primapi.grad over prim ops == reverse mode


class Jacobian:
    """Lazy row/column-sliceable Jacobian (reference: functional.py
    Jacobian)."""

    def __init__(self, func, xs, is_batched=False):
        self._mat = _jacobian(func, xs)

    def __getitem__(self, item):
        m = self._mat
        if isinstance(m, (list, tuple)):
            return [x[item] for x in m]
        return m[item]

    @property
    def shape(self):
        m = self._mat
        return m.shape if not isinstance(m, (list, tuple)) else \
            [x.shape for x in m]


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        self._mat = _hessian(func, xs)

    def __getitem__(self, item):
        return self._mat[item]

    @property
    def shape(self):
        return self._mat.shape
