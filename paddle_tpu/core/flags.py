"""Global flag system.

The reference defines ~185 env-overridable global flags via PHI_DEFINE_EXPORTED_*
(reference: paddle/common/flags.cc, flags.h:242) surfaced in python as
paddle.set_flags/get_flags (python/paddle/base/framework.py:132/:157).

Here flags are a plain process-global registry. Each flag has a type, default, and
doc; the environment variable ``FLAGS_<name>`` overrides the default at first read.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable

_lock = threading.Lock()
_REGISTRY: dict[str, "_Flag"] = {}


class _Flag:
    __slots__ = ("name", "type", "default", "doc", "_value", "_resolved", "on_change")

    def __init__(self, name, type_, default, doc, on_change=None):
        self.name = name
        self.type = type_
        self.default = default
        self.doc = doc
        self._value = default
        self._resolved = False
        self.on_change = on_change

    def _parse(self, s: str):
        if self.type is bool:
            return s.lower() in ("1", "true", "yes", "on")
        return self.type(s)

    def get(self):
        if not self._resolved:
            with _lock:
                if not self._resolved:
                    env = os.environ.get(f"FLAGS_{self.name}")
                    if env is not None:
                        self._value = self._parse(env)
                    self._resolved = True
        return self._value

    def set(self, value):
        with _lock:
            self._value = self.type(value) if not isinstance(value, self.type) else value
            self._resolved = True
        if self.on_change is not None:
            self.on_change(self._value)


def define_flag(name: str, default: Any, doc: str = "", type_: type | None = None,
                on_change: Callable | None = None):
    if type_ is None:
        type_ = type(default)
    flag = _Flag(name, type_, default, doc, on_change)
    _REGISTRY[name] = flag
    return flag


def get_flags(flags):
    """paddle.get_flags — accepts a name or list of names, returns {name: value}."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = _REGISTRY[key].get()
    return out


#: called (no args) after every set_flags — compiled caches that bake flag
#: values at trace time register here so a flag flip invalidates them
_ON_CHANGE_HOOKS: list = []


def register_flags_hook(fn):
    _ON_CHANGE_HOOKS.append(fn)


def set_flags(flags: dict):
    """paddle.set_flags — {name: value} (names may carry the FLAGS_ prefix)."""
    resolved = []
    for name, value in flags.items():  # validate ALL names before setting any
        key = name[6:] if name.startswith("FLAGS_") else name
        if key not in _REGISTRY:
            raise ValueError(f"unknown flag {name!r}")
        resolved.append((key, value))
    try:
        for key, value in resolved:
            _REGISTRY[key].set(value)
    finally:
        for hook in _ON_CHANGE_HOOKS:
            hook()


def flag_value(name: str):
    return _REGISTRY[name].get()


# --- core flags (analogs of the reference's most-used ones) ---
define_flag("check_nan_inf", False, "check every op output for nan/inf (numeric sanitizer)")
define_flag("use_fused_adamw", True,
            "route multi-precision Adam/AdamW updates to the fused Pallas "
            "single-pass kernel")
define_flag("use_pallas_int4", True,
            "route tileable weight-only int4 GEMMs to the fused Pallas "
            "dequant-matmul kernel (TPU backend only)")
define_flag("use_paged_attention", True,
            "route block_multihead_attention's paged decode through the "
            "Pallas paged-attention kernel (block-sparse KV reads off the "
            "physical pools, GQA, fused new-token write). TPU backends "
            "only — CPU always runs the dense-gather XLA fallback, so "
            "tier-1 stays kernel-free and deterministic. Set "
            "FLAGS_use_paged_attention=0 to A/B or debug against the "
            "fallback on TPU")
define_flag("adamw_bf16_moments", False,
            "store Adam/AdamW moment1/moment2 in bfloat16 (update math stays "
            "fp32 via upcast) — halves optimizer-state HBM traffic at a "
            "small stochastic-rounding cost; off by default to keep "
            "reference-exact trajectories")
define_flag("adamw_stochastic_rounding", False,
            "master-weight-FREE Adam/AdamW for bf16 params (multi_precision "
            "False): the fused Pallas kernel does fp32 math in VMEM and "
            "stochastically rounds the param write (E[round(x)]=x), so bf16 "
            "weights integrate small updates without an fp32 master copy — "
            "no master residency and ~36% less optimizer HBM traffic; off "
            "by default (changes trajectories vs the fp32-master reference)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; 1: warn; 3: report fp16 overflow too")
define_flag("benchmark", False, "synchronize after every op dispatch (op-level timing)")
define_flag("eager_op_jit", True, "route eager op dispatch through a cached jax.jit per op signature")
define_flag("log_level", 0, "vlog-style verbosity for framework internals")
define_flag("use_stride_kernel", True, "kept for API parity; views are always zero-copy under XLA")
define_flag("cudnn_deterministic", False, "kept for API parity; XLA:TPU is deterministic by default")
define_flag("embedding_deterministic", 0, "kept for API parity")
define_flag("collective_timeout_s", 600.0, "watchdog timeout for host-side collective ops")
