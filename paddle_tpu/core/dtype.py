"""Dtype model.

Paddle exposes a fixed dtype vocabulary (reference: paddle/phi/common/data_type.h,
python/paddle/framework/dtype.py). Here dtypes ARE numpy/ml_dtypes dtypes — the same
objects jax.numpy uses — so there is zero conversion cost at dispatch time. We keep
paddle's names and a string registry for `astype("float32")`-style calls.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects (np.dtype instances).
bool_ = np.dtype(np.bool_)
uint8 = np.dtype(np.uint8)
int8 = np.dtype(np.int8)
int16 = np.dtype(np.int16)
int32 = np.dtype(np.int32)
int64 = np.dtype(np.int64)
float16 = np.dtype(np.float16)
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype(np.float32)
float64 = np.dtype(np.float64)
complex64 = np.dtype(np.complex64)
complex128 = np.dtype(np.complex128)
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_NAME_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # paddle aliases
    "fp16": float16,
    "bf16": bfloat16,
    "fp32": float32,
    "fp64": float64,
}

_DEFAULT_DTYPE = float32


def convert_dtype(dtype) -> np.dtype:
    """Normalize any user-provided dtype spec (str, np dtype, python type) to np.dtype."""
    if dtype is None:
        raise ValueError("dtype must not be None")
    if isinstance(dtype, str):
        try:
            return _NAME_TO_DTYPE[dtype]
        except KeyError:
            raise ValueError(f"unsupported dtype string: {dtype!r}") from None
    if dtype is bool:
        return bool_
    if dtype is int:
        return int64
    if dtype is float:
        return _DEFAULT_DTYPE
    if dtype is complex:
        return complex64
    return np.dtype(dtype)


def set_default_dtype(d):
    """paddle.set_default_dtype — default float dtype for python-float tensor creation."""
    global _DEFAULT_DTYPE
    d = convert_dtype(d)
    if not np.issubdtype(d, np.floating) and d != bfloat16:
        raise TypeError(f"default dtype must be floating, got {d}")
    _DEFAULT_DTYPE = d


def get_default_dtype() -> np.dtype:
    return _DEFAULT_DTYPE


def is_floating_point_dtype(d) -> bool:
    d = np.dtype(d)
    return jnp.issubdtype(d, jnp.floating)


def is_integer_dtype(d) -> bool:
    d = np.dtype(d)
    return jnp.issubdtype(d, jnp.integer) or d == bool_


def is_complex_dtype(d) -> bool:
    return jnp.issubdtype(np.dtype(d), jnp.complexfloating)


def is_inexact_dtype(d) -> bool:
    return jnp.issubdtype(np.dtype(d), jnp.inexact)


def finfo(d):
    return jnp.finfo(convert_dtype(d))


def iinfo(d):
    return jnp.iinfo(convert_dtype(d))
