"""String tensors — the pstring/StringTensor analog.

Reference: paddle/phi/core/string_tensor.h:33 (StringTensor over
phi::dtype::pstring), kernels paddle/phi/kernels/strings/
{strings_empty_kernel.h, strings_copy_kernel.h, strings_lower_upper_kernel.h}
(each case op in an ASCII and a UTF-8 variant backed by
strings/unicode.h), and the C++ pstring type paddle/phi/common/pstring.h.

TPU-native positioning: XLA programs cannot hold variable-length strings, so
— exactly like the reference, whose string kernels are host/CPU-side and feed
id tensors to the compute graph — StringTensor here is a HOST tensor (numpy
object array of ``str``) with the reference's op surface (empty/copy/
lower/upper with the use_utf8_encoding switch), plus the two device bridges
that make it useful on a TPU:

  * ``to_bytes_tensor`` / ``from_bytes_tensor``: fixed-width uint8 encoding —
    the device-side representation of string data (padded UTF-8 bytes).
  * ``to_hash_ids``: stable 63-bit FNV-1a ids for hash-bucket embedding
    lookup, and ``lookup`` for explicit vocab → int64 ids.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "StringTensor", "empty", "empty_like", "copy", "lower", "upper",
    "to_bytes_tensor", "from_bytes_tensor", "to_hash_ids", "lookup",
]


def _ascii_case(s: str, to_lower: bool) -> str:
    # non-utf8 mode mirrors the reference's AsciiCaseConverter
    # (phi/kernels/strings/case_utils.h): only [A-Za-z] change.
    if to_lower:
        return "".join(
            chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s)
    return "".join(chr(ord(c) - 32) if "a" <= c <= "z" else c for c in s)


class StringTensor:
    """N-d host tensor of python strings (element type = the pstring analog)."""

    def __init__(self, data, name=None):
        if isinstance(data, StringTensor):
            arr = data._data.copy()
        else:
            arr = np.asarray(data, dtype=object)
            flat = arr.ravel()
            for i, v in enumerate(flat):
                if v is None:
                    flat[i] = ""
                elif isinstance(v, bytes):
                    flat[i] = v.decode("utf-8")
                elif not isinstance(v, str):
                    flat[i] = str(v)
        self._data = arr
        self.name = name or "string_tensor"

    # -- metadata ----------------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(self._data.size)

    @property
    def dtype(self):
        return "pstring"

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    # -- structural ops ----------------------------------------------------
    def reshape(self, shape):
        return StringTensor(self._data.reshape(shape))

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, str):
            return out
        return StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        other = other._data if isinstance(other, StringTensor) else other
        return self._data == other

    def __repr__(self):
        return (f"StringTensor(shape={self.shape}, "
                f"data={self._data.tolist()!r})")

    # -- element-wise case ops (method forms) ------------------------------
    def lower(self, use_utf8_encoding=False):
        return lower(self, use_utf8_encoding)

    def upper(self, use_utf8_encoding=False):
        return upper(self, use_utf8_encoding)


def _elementwise(x: StringTensor, fn) -> StringTensor:
    out = np.empty(x._data.shape, dtype=object)
    out_flat = out.ravel()
    for i, v in enumerate(x._data.ravel()):
        out_flat[i] = fn(v)
    return StringTensor(out)


def empty(shape) -> StringTensor:
    """reference: strings_empty_kernel.h — tensor of empty strings."""
    return StringTensor(np.full(tuple(shape), "", dtype=object))


def empty_like(x: StringTensor) -> StringTensor:
    return empty(x.shape)


def copy(x: StringTensor) -> StringTensor:
    """reference: strings_copy_kernel.h."""
    return StringTensor(x)


def lower(x: StringTensor, use_utf8_encoding=False) -> StringTensor:
    """reference: StringLowerKernel (strings_lower_upper_kernel.h:30);
    use_utf8_encoding=False converts ASCII letters only, True applies full
    Unicode case mapping (reference strings/unicode.h tables)."""
    if use_utf8_encoding:
        return _elementwise(x, str.lower)
    return _elementwise(x, lambda s: _ascii_case(s, True))


def upper(x: StringTensor, use_utf8_encoding=False) -> StringTensor:
    """reference: StringUpperKernel (strings_lower_upper_kernel.h:37)."""
    if use_utf8_encoding:
        return _elementwise(x, str.upper)
    return _elementwise(x, lambda s: _ascii_case(s, False))


# ---------------------------------------------------------------------------
# Device bridges
# ---------------------------------------------------------------------------

def to_bytes_tensor(x: StringTensor, width=None, pad=0):
    """Encode to a fixed-width uint8 device tensor (shape + [width]) of padded
    UTF-8 bytes — the form string data takes inside an XLA program. Returns
    (tensor, lengths_tensor)."""
    from ..ops import creation

    encoded = [s.encode("utf-8") for s in x._data.ravel()]
    if width is None:
        width = max((len(b) for b in encoded), default=0) or 1
    buf = np.full((len(encoded), width), pad, dtype=np.uint8)
    lens = np.zeros(len(encoded), dtype=np.int32)
    for i, b in enumerate(encoded):
        if len(b) > width:
            raise ValueError(
                f"string of {len(b)} utf-8 bytes exceeds width {width}")
        buf[i, :len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    return (creation.to_tensor(buf.reshape(tuple(x._data.shape) + (width,))),
            creation.to_tensor(lens.reshape(x._data.shape)))


def from_bytes_tensor(data, lengths) -> StringTensor:
    """Inverse of to_bytes_tensor."""
    arr = np.asarray(data.numpy() if hasattr(data, "numpy") else data,
                     dtype=np.uint8)
    lens = np.asarray(lengths.numpy() if hasattr(lengths, "numpy")
                      else lengths, dtype=np.int64)
    shape = arr.shape[:-1]
    flat = arr.reshape(-1, arr.shape[-1])
    lens_flat = lens.reshape(-1)
    out = np.empty(len(flat), dtype=object)
    for i in range(len(flat)):
        out[i] = bytes(flat[i, :lens_flat[i]]).decode("utf-8")
    return StringTensor(out.reshape(shape))


def _fnv1a63(b: bytes) -> int:
    h = 0xcbf29ce484222325
    for byte in b:
        h ^= byte
        h = (h * 0x100000001b3) & 0xFFFFFFFFFFFFFFFF
    return h & 0x7FFFFFFFFFFFFFFF  # non-negative int64


def to_hash_ids(x: StringTensor, num_buckets=None):
    """Stable FNV-1a ids (int64 device tensor) for hash-bucket embeddings —
    the id-tensor hand-off the reference's host-side string path feeds into
    the compute graph."""
    from ..ops import creation

    ids = np.array([_fnv1a63(s.encode("utf-8")) for s in x._data.ravel()],
                   dtype=np.int64)
    if num_buckets is not None:
        ids = ids % int(num_buckets)
    return creation.to_tensor(ids.reshape(x._data.shape))


def lookup(x: StringTensor, vocab, default=0):
    """Explicit vocab dict → int64 id tensor (OOV -> default)."""
    from ..ops import creation

    ids = np.array([vocab.get(s, default) for s in x._data.ravel()],
                   dtype=np.int64)
    return creation.to_tensor(ids.reshape(x._data.shape))
