"""Version compatibility shims for the jax API surface.

One symbol today: ``shard_map``. The framework's manual-collective code
(pipeline schedules, ring/Ulysses context parallelism, expert-parallel
MoE) is written against the modern top-level ``jax.shard_map`` API
(``axis_names=...`` for partial-manual meshes, ``check_vma=...``). On
jax < 0.5 that function lives at ``jax.experimental.shard_map.shard_map``
with the older kwargs (``auto`` = the complement of the manual axes,
``check_rep``); the adapter below translates so every call site can stay
written against the modern API.
"""
from __future__ import annotations

__all__ = ["shard_map"]

try:
    from jax import shard_map  # jax >= 0.5: the stable top-level API
except ImportError:  # pragma: no cover - depends on installed jax
    from jax.experimental.shard_map import shard_map as _experimental_sm

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None, **kwargs):
        """Modern-API adapter over the pre-0.5 experimental shard_map.

        * ``axis_names={...}`` (axes that are MANUAL) becomes
          ``auto = mesh.axis_names - axis_names`` (axes that stay
          automatic/GSPMD).
        * ``check_vma`` (renamed) becomes ``check_rep``.
        """
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - \
                frozenset(axis_names)
        return _experimental_sm(f, mesh=mesh, in_specs=in_specs,
                                out_specs=out_specs, **kwargs)
