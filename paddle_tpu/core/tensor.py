"""Eager Tensor + tape autograd.

Reference analog: the dygraph stack — AutogradMeta (paddle/fluid/eager/autograd_meta.h:61),
GradNodeBase (grad_node_info.h:197), TensorWrapper (tensor_wrapper.h:39), and the
generated per-op ad_func (eager_gen.py:372) that records grad nodes at forward time.

TPU-native design: every eager op goes through :func:`dispatch`. Forward compute is a
pure jax function; when gradients are required we call ``jax.vjp`` at forward time, so
the returned closure *is* the grad node — it owns the residuals (the TensorWrapper
analog) and jax derives the backward rule (no hand-written GradNode per op). The tape is
the DAG of ``Node`` objects linked through their input tensors; ``.backward()`` executes
it in reverse topological order (autograd/backward.py).

Inside ``jit``-traced (functional) code the same ops run tape-free on tracers, so one op
library serves both the eager and the compiled path — the analog of the reference's
single YAML op set feeding both eager and PIR engines.
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes
from .device import Place, get_place
from .flags import flag_value


# ---------------------------------------------------------------------------
# grad / functional mode state
# ---------------------------------------------------------------------------

class _ModeState(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.functional = 0  # >0 while tracing inside jit (tape disabled)


_mode = _ModeState()


def is_grad_enabled() -> bool:
    return _mode.grad_enabled and _mode.functional == 0


def set_grad_enabled(value: bool):
    _mode.grad_enabled = bool(value)


class _GradModeCtx:
    def __init__(self, target: bool):
        self._target = target

    def __enter__(self):
        self._saved = _mode.grad_enabled
        _mode.grad_enabled = self._target
        return self

    def __exit__(self, *exc):
        _mode.grad_enabled = self._saved
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with type(self)(self._target) if False else _GradModeCtx(self._target):
                return fn(*args, **kwargs)

        return wrapper


def no_grad():
    return _GradModeCtx(False)


def enable_grad():
    return _GradModeCtx(True)


class functional_mode:
    """Disables the tape while a jax transform traces through our ops."""

    def __enter__(self):
        _mode.functional += 1
        return self

    def __exit__(self, *exc):
        _mode.functional -= 1
        return False


def in_functional_mode() -> bool:
    return _mode.functional > 0


# ---------------------------------------------------------------------------
# tape node
# ---------------------------------------------------------------------------

class Node:
    """One recorded op. ``vjp_fn`` maps output cotangents -> input cotangents."""

    __slots__ = (
        "vjp_fn", "parents", "out_treedef", "out_avals", "outputs", "name", "fwd_fn",
        "__weakref__",
    )

    def __init__(self, vjp_fn, parents, out_treedef, out_avals, name, fwd_fn=None):
        self.vjp_fn = vjp_fn
        self.parents = parents          # list[Tensor] — differentiable inputs, vjp order
        self.out_treedef = out_treedef  # treedef of the op's full output pytree
        self.out_avals = out_avals      # ShapeDtypeStruct per output leaf
        self.outputs = []               # list[weakref to output Tensors | None] per leaf
        self.name = name
        # pure fn of the diff input *values* — used by create_graph (double grad) to
        # re-derive a vjp whose inputs are live tape tensors rather than baked residuals
        self.fwd_fn = fwd_fn

    def __repr__(self):
        return f"<Node {self.name} n_in={len(self.parents)} n_out={len(self.out_avals)}>"


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

def _is_tensor(x):
    return isinstance(x, Tensor)


# -- scalar-concretization interception (to_static graph-break machinery) ----
# When a traced program hits bool(t)/int(t)/t.item() on a tracer, jax raises a
# concretization error. to_static installs a scope here instead: in RECORD
# mode (eager profiling run) every concretized scalar is logged; in FEED mode
# (specialized re-trace) the logged profile is fed back as static values while
# the traced scalars are collected as guard outputs. See jit/api.py.

class _ConcretizeState(threading.local):
    """Per-thread (like _mode): a scope installed by thread A must not see
    scalars concretized by other threads (data loaders, metric threads)."""
    scope = None


_concretize_state = _ConcretizeState()


class ConcretizeScope:
    __slots__ = ("feed", "i", "recorded", "guards")

    def __init__(self, feed=None):
        self.feed = feed          # None = record mode; list = feed mode
        self.i = 0
        self.recorded = []
        self.guards = []

    def intercept(self, value, concrete=False):
        if self.feed is None:     # eager profiling: value is concrete
            v = value.item() if hasattr(value, "item") else value
            self.recorded.append(v)
            return v
        self.i += 1               # consume the slot either way: feed order
        if concrete:              # must mirror record order exactly
            # a concrete (non-traced) scalar inside the specialized trace:
            # its real value is authoritative and becomes a baked guard
            # constant — if it ever differs from the profile, validation
            # falls back to eager
            v = value.item() if hasattr(value, "item") else value
            self.guards.append(v)
            return v
        self.guards.append(value)
        return self.feed[self.i - 1]


class _ConcretizeCtx:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        self._saved = _concretize_state.scope
        _concretize_state.scope = self.scope
        return self.scope

    def __exit__(self, *exc):
        _concretize_state.scope = self._saved
        return False


def concretize_scope(scope):
    return _ConcretizeCtx(scope)


def _intercept_scalar(value):
    """Route a would-be concretization through the active scope, if any."""
    scope = _concretize_state.scope
    if scope is None:
        return None
    if scope.feed is None:
        return scope.intercept(value)
    if isinstance(value, jax.core.Tracer):
        return scope.intercept(value)
    # feed mode, concrete value (e.g. a closed-over eager tensor): record
    # mode logged it, so feed alignment must consume its slot too
    return scope.intercept(value, concrete=True)


class Tensor:
    """Eager tensor facade over ``jax.Array``.

    Reference analog: paddle::Tensor (paddle/phi/api/include/tensor.h:82) +
    AutogradMeta. ``stop_gradient`` defaults True like paddle's non-parameter tensors.
    """

    __slots__ = (
        "_value", "stop_gradient", "grad", "name", "_node", "_out_index",
        "_retain_grads", "_hooks", "persistable", "is_leaf_override", "__weakref__",
        "_dist_meta", "_feed_name",
    )

    def __init__(self, value, stop_gradient: bool = True, name: str | None = None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self.stop_gradient = stop_gradient
        self.grad = None
        self.name = name
        self._node = None
        self._out_index = 0
        self._retain_grads = False
        self._hooks = []
        self.persistable = False
        self.is_leaf_override = None
        self._dist_meta = None  # set by paddle_tpu.distributed for DistTensor semantics

    # -- basic properties ---------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self) -> list:
        return list(self._value.shape)

    @property
    def ndim(self) -> int:
        return self._value.ndim

    ndimension = ndim

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self) -> Place:
        try:
            dev = next(iter(self._value.devices()))
            kind = "tpu" if dev.platform in ("tpu", "axon") else dev.platform
            return Place(kind, dev.id)
        except Exception:
            return get_place()

    @property
    def is_leaf(self) -> bool:
        if self.is_leaf_override is not None:
            return self.is_leaf_override
        return self.stop_gradient or self._node is None

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return self.dtype.itemsize

    # -- conversion ---------------------------------------------------------
    def numpy(self) -> np.ndarray:
        if _capture.recorder is not None:
            # whole-array host read: the prefix-capture break point
            _capture.recorder.on_host_read(self._value)
        return np.asarray(self._value)

    def item(self):
        v = _intercept_scalar(self._value)
        return v if v is not None else self._value.item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        v = _intercept_scalar(self._value)
        return float(v) if v is not None else float(self._value.item())

    def __int__(self):
        v = _intercept_scalar(self._value)
        return int(v) if v is not None else int(self._value.item())

    def __index__(self):
        v = _intercept_scalar(self._value)
        return int(v) if v is not None else self._value.__index__()

    def __bool__(self):
        v = _intercept_scalar(self._value)
        return bool(v) if v is not None else bool(self._value)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    # -- autograd -----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from ..autograd.backward import run_backward
        run_backward([self], [grad_tensor], retain_graph=retain_graph)

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook: Callable):
        self._hooks.append(hook)

        class _Removable:
            def remove(_self):
                if hook in self._hooks:
                    self._hooks.remove(hook)

        return _Removable()

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        t._dist_meta = self._dist_meta
        return t

    def clone(self) -> "Tensor":
        from .. import ops
        return ops.assign(self)

    @property
    def gradient(self):
        return None if self.grad is None else self.grad.numpy()

    # -- in-place value rebinding (optimizer updates, __setitem__) ----------
    def _replace_value(self, new_value):
        self._value = new_value
        return self

    def copy_(self, other, blocking: bool = True):
        src = other._value if isinstance(other, Tensor) else jnp.asarray(other)
        self._value = jnp.asarray(src, dtype=self._value.dtype)
        return self

    def set_value(self, other):
        return self.copy_(other)

    # -- repr ---------------------------------------------------------------
    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        try:
            data = np.array2string(self.numpy(), precision=6, threshold=64)
        except Exception:
            data = "<traced>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"place={self.place}{grad_info},\n       {data})")

    # arithmetic/method surface is attached in paddle_tpu/__init__.py via
    # _bind_tensor_methods() once the ops library is importable (avoids an
    # import cycle ops -> tensor -> ops).


# Register Tensor as a pytree node so jax transforms can carry it transparently
# (values only; autograd metadata does not survive a tree round-trip on purpose).
jax.tree_util.register_pytree_node(
    Tensor,
    lambda t: ((t._value,), (t.stop_gradient, t.name)),
    lambda aux, children: Tensor(children[0], stop_gradient=aux[0], name=aux[1]),
)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


# observers called as (op_name, out_leaves) after every dispatch — used by
# amp.debugging operator-stats collection; empty in the hot path
_OP_OBSERVERS: list = []


def _check_numerics(name, leaves):
    level = flag_value("check_nan_inf_level")
    for v in leaves:
        if isinstance(v, jax.Array) and jnp.issubdtype(v.dtype, jnp.inexact):
            bad = bool(jnp.any(~jnp.isfinite(v)))
            if bad:
                msg = f"[check_nan_inf] op {name!r} produced nan/inf in output {v.shape} {v.dtype}"
                if level >= 1:
                    import logging
                    logging.getLogger("paddle_tpu").warning(msg)
                else:
                    raise FloatingPointError(msg)


_amp_cast_fn = None


def _maybe_amp_cast(name, vals):
    """AMP autocast hook — the injection point the reference generates into every
    ad_func (eager_gen.py AMP logic). Lazily bound to avoid an import cycle."""
    global _amp_cast_fn
    if _amp_cast_fn is None:
        return vals
    return _amp_cast_fn(name, vals)


def install_amp_hook(fn):
    global _amp_cast_fn
    _amp_cast_fn = fn


# -- compiled eager dispatch -------------------------------------------------
# The reference spends 4.2k lines of codegen making per-op eager dispatch
# allocation-free (fluid/eager/auto_code_generator/generator/eager_gen.py:372).
# Here the analog is a compile cache: for REGISTERED ops (stable fn identity),
# the forward—and, when recording, the jax.vjp pair—is jitted once per
# (op, structure, static args, shapes/dtypes, diff-mask) and reused, so an
# eager op call is one compiled-executable invocation instead of an un-jitted
# trace + fresh vjp construction. Ad-hoc closures (functional wrappers) keep
# the direct path; ops observed drawing RNG during trace are blacklisted so
# their randomness never bakes into a cached executable.

_DISPATCH_CACHE: dict = {}   # insertion-ordered; maintained as LRU
_UNCACHEABLE_OPS: set = set()
_CACHE_BYPASS = object()
_BWD_JIT = None
_DISPATCH_CACHE_MAX = 4096
#: observability for the eager hot path (reference: the codegen'd dispatch
#: counters); read via dispatch_cache_stats(), reset on clear
_CACHE_STATS = {"hits": 0, "misses": 0, "evictions": 0, "bypasses": 0,
                "evict_streak": 0}


def dispatch_cache_stats() -> dict:
    """Hit/miss/eviction/bypass counters plus current size of the compiled
    eager-dispatch cache."""
    return dict(_CACHE_STATS, size=len(_DISPATCH_CACHE),
                max_size=_DISPATCH_CACHE_MAX)


# -- compiled-prefix capture hooks (jit/prefix_capture.py) -------------------
class _CaptureState(threading.local):
    """Thread-local recorder/replay hooks — like _mode, so a concurrent
    thread dispatching during record/replay can neither interleave its ops
    into the captured prefix nor race the replay cursor."""

    def __init__(self):
        #: when set, every dispatch on THIS thread is logged with argument
        #: provenance (record mode)
        self.recorder = None
        #: when set, prefix-position dispatches on THIS thread are answered
        #: from a compiled prefix
        self.replay = None


_capture = _CaptureState()
#: sentinel: the replay state declined this op (past the prefix) — dispatch
#: proceeds normally
_REPLAY_PASS = object()


class _Unfreezable(Exception):
    pass


def _freeze(v, depth=0):
    """Hashable, value-stable token for an op callable: its code object plus
    recursively frozen closure cells/defaults. Only immutable primitives are
    admitted as cell values — anything stateful (arrays, Tensors, lists,
    layers) raises, which routes that call to the uncached path."""
    if depth > 3:
        raise _Unfreezable
    if v is None:
        return v
    if isinstance(v, (int, float, bool, str, bytes)):
        # type-tag scalars: 1, 1.0 and True hash/compare equal but trace to
        # different programs
        return (type(v), v)
    if isinstance(v, type):
        return ("T", v)
    if isinstance(v, np.dtype):
        return ("D", str(v))
    if isinstance(v, (tuple, list)):
        # lists freeze by VALUE — the key reflects call-time contents, so a
        # mutated list simply maps to a different cache entry
        return ("t",) + tuple(_freeze(e, depth + 1) for e in v)
    if isinstance(v, dict):
        return ("d",) + tuple((k, _freeze(e, depth + 1))
                              for k, e in sorted(v.items(), key=repr))
    if callable(v):
        code = getattr(v, "__code__", None)
        if code is not None:
            # A bound method's __code__/__closure__ belong to the underlying
            # function; two methods of different instances would collide. The
            # instance itself is almost always stateful, so freeze it too —
            # stateful selves raise and route to the uncached path.
            slf = getattr(v, "__self__", None)
            frozen_self = _freeze(slf, depth + 1) if slf is not None else None
            cells = getattr(v, "__closure__", None) or ()
            frozen = tuple(_freeze(c.cell_contents, depth + 1) for c in cells)
            defaults = tuple(_freeze(d, depth + 1)
                             for d in (getattr(v, "__defaults__", None) or ()))
            return ("F", code, frozen_self, frozen, defaults)
        mod = getattr(v, "__module__", None) or \
            getattr(type(v), "__module__", "")
        if str(mod).startswith(("jax", "numpy")):
            # module-level jax/numpy callables (incl. ufunc objects): key by
            # (module, qualname) — stable for the process lifetime — but only
            # after confirming the name genuinely resolves back to v, so
            # dynamically created instances (np.vectorize etc.) can't alias
            # a module attr or leak via pinned id()s
            name = getattr(v, "__qualname__", None) or \
                getattr(v, "__name__", None)
            if name is not None:
                import sys
                target = sys.modules.get(str(mod))
                for part in str(name).split("."):
                    target = getattr(target, part, None)
                    if target is None:
                        break
                if target is v:
                    return ("G", str(mod), str(name))
    raise _Unfreezable


def clear_dispatch_cache():
    _DISPATCH_CACHE.clear()
    for k in _CACHE_STATS:
        _CACHE_STATS[k] = 0


# flag flips invalidate cached executables (op bodies read flags at trace
# time); clearing beats epoch-keying, which would orphan entries at the cap
from .flags import register_flags_hook as _register_flags_hook  # noqa: E402
_register_flags_hook(clear_dispatch_cache)


def _bwd_call(vjp_obj, ct):
    """Apply a cached VJP closure under jit (float0 cotangents go eagerly —
    they don't cross the jit boundary)."""
    global _BWD_JIT
    for leaf in jax.tree_util.tree_leaves(ct):
        if isinstance(leaf, np.ndarray) and leaf.dtype == jax.dtypes.float0:
            return vjp_obj(ct)
    if _BWD_JIT is None:
        _BWD_JIT = jax.jit(lambda v, c: v(c))
    return _BWD_JIT(vjp_obj, ct)


def _rng_counters():
    from . import random as _random
    prov = _random._key_providers
    # _draw_epoch counts draws from EVERY Generator (default + tracker
    # streams), so a first trace that consumes randomness through any of
    # them gets blacklisted, not just draws through default_generator
    return (_random._draw_epoch,
            prov[-1].counter if prov else -1)


def _dispatch_cached(fn, name, cache_key, leaves, treedef, record):
    """Compiled-path dispatch. Returns _CACHE_BYPASS when this call can't be
    cached (unhashable static leaf / RNG draw detected on first trace)."""
    layout, dyn_vals, statics, diff_idx, diff_tensors = [], [], [], [], []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            layout.append("D")
            if record and not leaf.stop_gradient:
                diff_idx.append(len(dyn_vals))
                diff_tensors.append(leaf)
            dyn_vals.append(leaf._value)
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            layout.append("D")
            dyn_vals.append(leaf)
        else:
            try:
                hash(leaf)
            except TypeError:
                return _CACHE_BYPASS
            layout.append("S")
            statics.append(leaf)

    dyn_vals = _maybe_amp_cast(name, dyn_vals)
    key = (cache_key, record, treedef, tuple(layout),
           tuple((type(s), s) for s in statics),  # 1 != 1.0 != True as keys
           tuple(diff_idx),
           tuple((tuple(getattr(v, "shape", ())), str(getattr(v, "dtype", type(v))))
                 for v in dyn_vals))

    entry = _DISPATCH_CACHE.get(key)
    first = entry is None
    if not first:
        # LRU maintenance: re-insert at the MRU end so long-running jobs
        # with shape churn (variable seq lens, generation loops) keep their
        # hot entries instead of freezing the first 4096 shapes forever
        _DISPATCH_CACHE[key] = _DISPATCH_CACHE.pop(key)
        _CACHE_STATS["hits"] += 1
        _CACHE_STATS["evict_streak"] = 0
    else:
        _CACHE_STATS["misses"] += 1
        if _DISPATCH_CACHE_MAX <= 0:
            _CACHE_STATS["bypasses"] += 1
            return _CACHE_BYPASS
        if _CACHE_STATS["evict_streak"] > _DISPATCH_CACHE_MAX // 4:
            # thrash guard: a working set that cycles without EVER hitting
            # (e.g. unbucketed lengths > cache size) must not pay a jit
            # trace+compile per dispatch — serve it from the direct path
            # like the old insert-cap did; hits on resident entries still
            # reset the streak and re-enable inserts
            _CACHE_STATS["bypasses"] += 1
            return _CACHE_BYPASS
        while len(_DISPATCH_CACHE) >= _DISPATCH_CACHE_MAX:
            _DISPATCH_CACHE.pop(next(iter(_DISPATCH_CACHE)))
            _CACHE_STATS["evictions"] += 1
            _CACHE_STATS["evict_streak"] += 1
    if first:
        layout_t, statics_t, di = tuple(layout), tuple(statics), tuple(diff_idx)

        def rebuilt(vals_dyn):
            it, st = iter(vals_dyn), iter(statics_t)
            vals = [next(it) if tag == "D" else next(st) for tag in layout_t]
            a, k = jax.tree_util.tree_unflatten(treedef, vals)
            return fn(*a, **k)

        if record:
            def fwd(vals_dyn):
                def closed(*diff_vals):
                    vv = list(vals_dyn)
                    for j, v in zip(di, diff_vals):
                        vv[j] = v
                    return rebuilt(vv)
                return jax.vjp(closed, *[vals_dyn[j] for j in di])
            entry = (jax.jit(fwd), rebuilt)
        else:
            entry = (jax.jit(rebuilt), rebuilt)
        _DISPATCH_CACHE[key] = entry

    jitted, rebuilt = entry
    if first:
        rng_before = _rng_counters()
    result = jitted(dyn_vals)
    if first and _rng_counters() != rng_before:
        # the op drew randomness during its trace — a cached executable would
        # replay the same key forever; evict and take the direct path
        del _DISPATCH_CACHE[key]
        _UNCACHEABLE_OPS.add(cache_key)
        return _CACHE_BYPASS

    if not record:
        return _wrap_outputs(result, node=None, name=name)

    out, vjp_obj = result
    base_vals = list(dyn_vals)
    di = tuple(diff_idx)

    def closed_eager(*diff_vals):
        vv = list(base_vals)
        for j, v in zip(di, diff_vals):
            vv[j] = v
        return rebuilt(vv)

    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_leaves]
    node = Node(functools.partial(_bwd_call, vjp_obj), diff_tensors,
                out_treedef, out_avals, name, fwd_fn=closed_eager)
    return _wrap_outputs(out, node=node, name=name)


def dispatch(fn: Callable, args: tuple, kwargs: dict, name: str | None = None,
             cache_key: str | None = None):
    """Run one op eagerly, recording a tape node when gradients are required.

    ``fn`` must be a pure jax function of the *values* inside any Tensor leaves of
    (args, kwargs). Non-tensor leaves are closed over (static from autograd's view).
    ``cache_key`` (set by the op registry) opts the call into the compiled
    dispatch cache — only valid when ``fn`` is a stable pure function.
    """
    name = name or getattr(fn, "__name__", "op")
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs), is_leaf=_is_tensor)

    tensor_pos = [i for i, leaf in enumerate(leaves) if isinstance(leaf, Tensor)]
    record = (
        is_grad_enabled()
        and any(not leaves[i].stop_gradient for i in tensor_pos)
    )

    rep = _capture.replay
    if rep is not None:
        # compiled-prefix replay (jit/prefix_capture.py): prefix-position
        # ops are answered from the precompiled program; divergence (or a
        # grad-recording op) ends the replay and execution continues eagerly
        out = rep.try_replay(fn, name, leaves, treedef, record)
        if out is not _REPLAY_PASS:
            return out

    rec = _capture.recorder
    if cache_key is None and not _OP_OBSERVERS and _mode.functional == 0 \
            and rec is None:
        try:
            cache_key = (name, _freeze(fn))
        except (_Unfreezable, ValueError):  # ValueError: empty closure cell
            cache_key = None
    if cache_key is not None and cache_key not in _UNCACHEABLE_OPS \
            and not _OP_OBSERVERS and _mode.functional == 0 and rec is None:
        out = _dispatch_cached(fn, name, cache_key, leaves, treedef, record)
        if out is not _CACHE_BYPASS:
            return out

    rng_before = _rng_counters() if rec is not None else None

    if not record:
        vals = _maybe_amp_cast(name, [_unwrap(x) for x in leaves])
        a, k = jax.tree_util.tree_unflatten(treedef, vals)
        out = fn(*a, **k)
        result = _wrap_outputs(out, node=None, name=name)
        if rec is not None:
            rec.after_op(fn, name, leaves, treedef, result, False,
                         _rng_counters() != rng_before)
        return result

    diff_pos = [i for i in tensor_pos if not leaves[i].stop_gradient]
    diff_tensors = [leaves[i] for i in diff_pos]
    base_vals = _maybe_amp_cast(name, [_unwrap(x) for x in leaves])

    def closed(*diff_vals):
        vals = list(base_vals)
        for p, v in zip(diff_pos, diff_vals):
            vals[p] = v
        a, k = jax.tree_util.tree_unflatten(treedef, vals)
        return fn(*a, **k)

    out, vjp_fn = jax.vjp(closed, *[base_vals[i] for i in diff_pos])
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in out_leaves]
    node = Node(vjp_fn, diff_tensors, out_treedef, out_avals, name, fwd_fn=closed)
    result = _wrap_outputs(out, node=node, name=name)
    if rec is not None:
        rec.after_op(fn, name, leaves, treedef, result, True,
                     _rng_counters() != rng_before)
    return result


def _wrap_outputs(out, node: Node | None, name: str):
    out_leaves, out_treedef = jax.tree_util.tree_flatten(out)
    if flag_value("check_nan_inf"):
        _check_numerics(name, out_leaves)
    for _obs in _OP_OBSERVERS:
        _obs(name, out_leaves)
    wrapped = []
    for i, leaf in enumerate(out_leaves):
        if not isinstance(leaf, (jax.Array, np.ndarray)) and not hasattr(leaf, "dtype"):
            wrapped.append(leaf)
            if node is not None:
                node.outputs.append(None)
            continue
        diff_out = node is not None and jnp.issubdtype(leaf.dtype, jnp.inexact)
        t = Tensor(leaf, stop_gradient=not diff_out)
        if node is not None:
            t._node = node
            t._out_index = i
            node.outputs.append(weakref.ref(t))
        wrapped.append(t)
    result = jax.tree_util.tree_unflatten(out_treedef, wrapped)
    return result


class OpDef:
    """Registered op: a named pure function invokable on Tensors via dispatch."""

    __slots__ = ("fn", "name", "__wrapped__")

    def __init__(self, fn, name=None):
        self.fn = fn
        self.name = name or fn.__name__
        self.__wrapped__ = fn

    def __call__(self, *args, **kwargs):
        return dispatch(self.fn, args, kwargs, name=self.name,
                        cache_key=self.name)

    def __repr__(self):
        return f"<op {self.name}>"


_OP_REGISTRY: dict[str, OpDef] = {}


def register_op(fn=None, *, name: str | None = None):
    """Decorator: make a pure jax function an eager-dispatchable op.

    The registry is the analog of the reference KernelFactory
    (paddle/phi/core/kernel_factory.h:316) — a flat name->callable map; backend
    selection is XLA's job, not ours.
    """
    def deco(f):
        op = OpDef(f, name)
        _OP_REGISTRY[op.name] = op
        return op

    return deco(fn) if fn is not None else deco


def get_op(name: str) -> OpDef:
    return _OP_REGISTRY[name]


def all_ops() -> dict:
    return dict(_OP_REGISTRY)
