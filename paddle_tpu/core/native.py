"""ctypes bindings for the native runtime layer (paddle_tpu/csrc).

Reference analog: the pybind layer (paddle/fluid/pybind) — except the TPU build
binds a small C ABI (csrc/pt_native.h) via ctypes, so there is no compiled
Python-extension coupling. The library auto-builds from source on first use
(`make -C paddle_tpu/csrc`) and every consumer has a pure-Python fallback, so
the framework works even without a toolchain.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_CSRC_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                         "csrc")
_LIB_PATH = os.path.join(_CSRC_DIR, "libpaddle_tpu_rt.so")

_lock = threading.Lock()
_lib = None
_tried = False


class ScanResult(ctypes.Structure):
    _fields_ = [
        ("nan_count", ctypes.c_longlong),
        ("inf_count", ctypes.c_longlong),
        ("zero_count", ctypes.c_longlong),
        ("finite_count", ctypes.c_longlong),
        ("abs_max", ctypes.c_double),
        ("min", ctypes.c_double),
        ("max", ctypes.c_double),
        ("sum", ctypes.c_double),
    ]


def _configure(lib):
    lib.pt_store_server_start.restype = ctypes.c_void_p
    lib.pt_store_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                          ctypes.POINTER(ctypes.c_int)]
    lib.pt_store_server_stop.argtypes = [ctypes.c_void_p]
    lib.pt_store_server_num_keys.restype = ctypes.c_uint64
    lib.pt_store_server_num_keys.argtypes = [ctypes.c_void_p]

    lib.pt_shm_create.restype = ctypes.c_void_p
    lib.pt_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.pt_shm_open.restype = ctypes.c_void_p
    lib.pt_shm_open.argtypes = [ctypes.c_char_p]
    lib.pt_shm_push.restype = ctypes.c_int
    lib.pt_shm_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_size_t, ctypes.c_int]
    lib.pt_shm_pop.restype = ctypes.c_int
    lib.pt_shm_pop.argtypes = [ctypes.c_void_p,
                               ctypes.POINTER(ctypes.c_void_p),
                               ctypes.POINTER(ctypes.c_size_t), ctypes.c_int]
    lib.pt_shm_close.argtypes = [ctypes.c_void_p]
    lib.pt_shm_destroy.argtypes = [ctypes.c_void_p]
    lib.pt_shm_capacity.restype = ctypes.c_size_t
    lib.pt_shm_capacity.argtypes = [ctypes.c_void_p]
    lib.pt_buf_free.argtypes = [ctypes.c_void_p]

    lib.pt_scan_floats.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                   ctypes.c_int, ctypes.c_int,
                                   ctypes.POINTER(ScanResult)]

    lib.pt_ps_server_start.restype = ctypes.c_void_p
    lib.pt_ps_server_start.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_int)]
    lib.pt_ps_server_stop.argtypes = [ctypes.c_void_p]
    return lib


def load():
    """Load (building if necessary) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Rebuild when the .so is missing or older than any source — a
        # prebuilt .so from an older tree would load but miss newer symbols.
        # The build itself is serialized across processes with flock so
        # concurrently-starting workers don't race g++ over the same outputs.
        if _stale():
            try:
                import fcntl
                with open(os.path.join(_CSRC_DIR, ".build.lock"), "w") as lk:
                    fcntl.flock(lk, fcntl.LOCK_EX)
                    if _stale():  # first holder built it
                        subprocess.run(["make", "-C", _CSRC_DIR],
                                       capture_output=True, timeout=120,
                                       check=True)
            except Exception:
                if not os.path.exists(_LIB_PATH):
                    return None
        try:
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except (OSError, AttributeError):
            _lib = None
        return _lib


def _stale():
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    for fn in os.listdir(_CSRC_DIR):
        if fn.endswith((".cc", ".h")) or fn == "Makefile":
            if os.path.getmtime(os.path.join(_CSRC_DIR, fn)) > lib_mtime:
                return True
    return False


def available() -> bool:
    return load() is not None


# ---------------------------------------------------------------------------
# ShmChannel wrapper
# ---------------------------------------------------------------------------

class ShmChannel:
    """MPSC shared-memory byte channel (creator = consumer side)."""

    def __init__(self, name: str, capacity: int | None = None, create=True):
        lib = load()
        if lib is None:
            raise RuntimeError("native runtime library unavailable")
        self._lib = lib
        self.name = name
        if create:
            self._h = lib.pt_shm_create(name.encode(), int(capacity or 64 << 20))
        else:
            self._h = lib.pt_shm_open(name.encode())
        if not self._h:
            raise OSError(f"cannot {'create' if create else 'open'} shm {name}")
        self._owner = create

    def push(self, data: bytes, timeout_ms=-1):
        rc = self._lib.pt_shm_push(self._h, data, len(data), timeout_ms)
        if rc == -1:
            raise TimeoutError("shm push timed out")
        if rc == -2:
            raise BrokenPipeError("shm channel closed")
        if rc == -3:
            raise ValueError(f"message of {len(data)} bytes exceeds channel "
                             f"capacity {self.capacity}")

    def pop(self, timeout_ms=-1) -> bytes:
        out = ctypes.c_void_p()
        out_len = ctypes.c_size_t()
        rc = self._lib.pt_shm_pop(self._h, ctypes.byref(out),
                                  ctypes.byref(out_len), timeout_ms)
        if rc == -1:
            raise TimeoutError("shm pop timed out")
        if rc == -2:
            raise BrokenPipeError("shm channel closed")
        try:
            return ctypes.string_at(out.value, out_len.value)
        finally:
            self._lib.pt_buf_free(out)

    @property
    def capacity(self):
        return int(self._lib.pt_shm_capacity(self._h))

    def close(self):
        if self._h:
            self._lib.pt_shm_close(self._h)

    def destroy(self):
        if self._h:
            self._lib.pt_shm_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            if getattr(self, "_h", None) and self._owner:
                self.destroy()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# numeric scan
# ---------------------------------------------------------------------------

_KIND = {"float32": 0, "float64": 1, "bfloat16": 2, "float16": 3}


def scan_array(arr, num_threads=0):
    """nan/inf/absmax/sum audit of a numpy (or numpy-convertible) array.

    Returns dict(nan_count, inf_count, abs_max, sum) or None when the dtype is
    unsupported or the native lib is missing (caller falls back to numpy).
    """
    import numpy as np

    lib = load()
    if lib is None:
        return None
    a = np.ascontiguousarray(arr)
    name = str(a.dtype)
    if name not in _KIND:
        return None
    res = ScanResult()
    lib.pt_scan_floats(a.ctypes.data_as(ctypes.c_void_p), a.size, _KIND[name],
                       num_threads, ctypes.byref(res))
    return {"nan_count": int(res.nan_count), "inf_count": int(res.inf_count),
            "zero_count": int(res.zero_count),
            "finite_count": int(res.finite_count),
            "abs_max": float(res.abs_max), "min": float(res.min),
            "max": float(res.max), "sum": float(res.sum)}
