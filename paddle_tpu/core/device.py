"""Place/device model.

Reference: ``phi::Place`` (paddle/phi/common/place.h:58) names a device slot;
DeviceContext/streams are per-place. Under PJRT there is no user-managed stream or
allocator — a Place is just a ``jax.Device`` — so this module is a thin naming layer:
``TPUPlace(i)``/``CPUPlace()`` map to jax devices, and ``set_device`` picks the default
placement for newly created tensors.
"""
from __future__ import annotations

import jax


class Place:
    """Named device slot, resolvable to a jax.Device."""

    def __init__(self, kind: str, index: int = 0):
        self.kind = kind
        self.index = index

    def jax_device(self) -> jax.Device:
        devs = [d for d in jax.devices() if _kind_of(d) == self.kind]
        if not devs:
            # transparent fallback (e.g. TPUPlace in a CPU test env)
            devs = jax.devices()
        return devs[min(self.index, len(devs) - 1)]

    def is_tpu_place(self):
        return self.kind == "tpu"

    def is_cpu_place(self):
        return self.kind == "cpu"

    def is_gpu_place(self):  # parity shim: CUDAPlace maps onto the accelerator
        return self.kind in ("gpu", "tpu")

    def __eq__(self, other):
        return isinstance(other, Place) and (self.kind, self.index) == (other.kind, other.index)

    def __hash__(self):
        return hash((self.kind, self.index))

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"


def TPUPlace(index: int = 0) -> Place:
    return Place("tpu", index)


def CPUPlace() -> Place:
    return Place("cpu", 0)


def CUDAPlace(index: int = 0) -> Place:  # parity alias: the accelerator place
    return Place("tpu", index)


XPUPlace = TPUPlace
CustomPlace = TPUPlace


def _kind_of(dev: jax.Device) -> str:
    plat = dev.platform
    if plat in ("tpu", "axon"):
        return "tpu"
    return plat


_current_place: Place | None = None


def set_device(device: str | Place) -> Place:
    """paddle.device.set_device — "tpu", "tpu:1", "cpu"."""
    global _current_place
    if isinstance(device, Place):
        _current_place = device
        return _current_place
    kind, _, idx = device.partition(":")
    kind = {"gpu": "tpu", "cuda": "tpu", "xpu": "tpu"}.get(kind, kind)
    _current_place = Place(kind, int(idx) if idx else 0)
    return _current_place


def get_device() -> str:
    p = get_place()
    return f"{p.kind}:{p.index}"


def get_place() -> Place:
    global _current_place
    if _current_place is None:
        accel = [d for d in jax.devices() if d.platform != "cpu"]
        _current_place = Place(_kind_of(accel[0]), 0) if accel else Place("cpu", 0)
    return _current_place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return True


def device_count() -> int:
    return len(jax.devices())
