from . import dtype, device, flags, random, tensor  # noqa: F401
