"""RNG state model.

The reference keeps a per-device Philox ``phi::Generator`` (paddle/phi/core/generator.h:32)
plus a tensor-parallel ``RNGStatesTracker`` for deterministic parallel dropout
(python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py).

TPU-native design: state is a jax PRNG key (threefry), advanced functionally. Eager ops
draw subkeys from the global Generator; named substates (the RNGStatesTracker analog)
are derived with ``jax.random.fold_in`` so e.g. the "local_seed" stream used inside a
model-parallel region differs per mesh coordinate while the "global_seed" stream does not.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


# Monotone count of stateful draws across EVERY Generator instance (default,
# tracker streams, user-created). The eager dispatch cache snapshots this to
# detect any RNG consumption during a first trace — watching only
# default_generator._counter would miss draws from tracker generators.
# Guarded by its own lock: per-instance locks don't serialize increments from
# different generators, and a lost increment could hide a draw from the
# cache's before/after snapshot.
_draw_epoch = 0
_epoch_lock = threading.Lock()


def _bump_draw_epoch():
    global _draw_epoch
    with _epoch_lock:
        _draw_epoch += 1


class Generator:
    """Stateful key holder. ``next_key()`` splits off a fresh subkey.

    The device key is created LAZILY: ``jax.random.key`` initializes the
    backend, and importing the framework must not touch the device (host-only
    tools — launcher, store, data pipeline — run without one)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        with getattr(self, "_lock", threading.Lock()):
            self._seed = int(seed)
            self._key = None  # materialized on first device use
            self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        with self._lock:
            key = self._key
            if key is None:
                key = jax.random.key(self._seed)
                # don't cache a key materialized during a trace — it would
                # leak the tracer into later eager calls
                if not isinstance(key, jax.core.Tracer):
                    self._key = key
            self._counter += 1
            _bump_draw_epoch()
            return jax.random.fold_in(key, self._counter)

    def next_seed(self):
        """Host-side draw: a fresh (seed, counter) pair for numpy RNGs (no
        device work). Used by host-resident samplers (e.g. graph sampling)."""
        with self._lock:
            self._counter += 1
            _bump_draw_epoch()
            return (self._seed, self._counter)

    def get_state(self):
        with self._lock:
            return (self._seed, self._counter)

    def set_state(self, state):
        seed, counter = state
        with self._lock:
            self._seed = int(seed)
            self._key = None
            self._counter = int(counter)


default_generator = Generator(0)

# --- traced-key plumbing -----------------------------------------------------
# Inside a jit-traced train step, drawing from the stateful Generator would bake a
# constant key into the compiled program. A KeyProvider scope makes `next_key()`
# derive keys from a *traced* base key instead (fold_in with a per-trace counter),
# so randomness varies with the step key input. The jit/to_static layer installs one.

import contextlib

_key_providers: list = []


class _KeyProvider:
    __slots__ = ("key", "counter")

    def __init__(self, key):
        self.key = key
        self.counter = 0


@contextlib.contextmanager
def provide_key(key):
    _key_providers.append(_KeyProvider(key))
    try:
        yield
    finally:
        _key_providers.pop()


def seed(value: int):
    """paddle.seed — reseed the global generator (and all named trackers)."""
    default_generator.manual_seed(value)
    _tracker.reset_from(value)
    return default_generator


def next_key():
    if _key_providers:
        p = _key_providers[-1]
        p.counter += 1
        return jax.random.fold_in(p.key, p.counter)
    return default_generator.next_key()


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG streams (reference: fleet/meta_parallel/parallel_layers/random.py).

    Tensor-parallel dropout must be identical across TP ranks for replicated
    activations ("global_seed") but different per rank for partitioned activations
    ("local_seed"). Streams are independent Generators derived from a base seed.
    """

    def __init__(self):
        self._states: dict[str, Generator] = {}
        self._base = 0

    def reset_from(self, base_seed: int):
        self._base = int(base_seed)
        for i, name in enumerate(sorted(self._states)):
            self._states[name].manual_seed(self._mix(name))

    def _mix(self, name: str) -> int:
        h = np.uint64(14695981039346656037)
        for b in name.encode():
            h = (h ^ np.uint64(b)) * np.uint64(1099511628211)
        return int((np.uint64(self._base) ^ h) % np.uint64(2**63))

    def add(self, name: str, seed: int | None = None):
        if name in self._states:
            raise ValueError(f"rng state {name!r} already exists")
        self._states[name] = Generator(self._mix(name) if seed is None else seed)

    def states(self):
        return dict(self._states)

    class _Scope:
        def __init__(self, tracker, name):
            self._tracker, self._name = tracker, name

        def __enter__(self):
            self._saved = default_generator
            _swap_default(self._tracker._states[self._name])
            return self

        def __exit__(self, *exc):
            _swap_default(self._saved)
            return False

    def rng_state(self, name: str = "global_seed"):
        if name not in self._states:
            self.add(name)
        return RNGStatesTracker._Scope(self, name)


_tracker = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _tracker


def _swap_default(gen: Generator):
    global default_generator
    default_generator = gen
