"""Functional-state bridge: run stateful Layers under jax transforms.

The reference needs a whole subsystem to capture python programs into a graph
(SOT bytecode interception — python/paddle/jit/sot; AST transform — jit/dy2static).
Here capture is jax tracing: we temporarily rebind every Parameter/buffer `_value`
to a traced array and call the same eager code. One model definition, two engines —
the analog of the reference's dygraph/static duality without a second IR.
"""
from __future__ import annotations

import contextlib
from typing import Sequence

import jax

from ..core.tensor import Tensor, functional_mode
from ..nn.layer_base import Layer


def collect_state(layers) -> tuple[list[str], list[Tensor], list[str], list[Tensor]]:
    """Gather (param_names, params, buffer_names, buffers) across layers, deduped."""
    # unwrap delegating model wrappers (DataParallel/_HybridShardedModel/
    # GroupShardedStage3 all proxy a real Layer behind `_model`)
    while not isinstance(layers, (Layer, list, tuple)) \
            and getattr(layers, "_model", None) is not None:
        layers = layers._model
    if isinstance(layers, Layer):
        layers = [layers]
    pnames, params, bnames, buffers = [], [], [], []
    seen = set()
    for li, layer in enumerate(layers):
        prefix = f"layer{li}." if len(layers) > 1 else ""
        for n, p in layer.named_parameters():
            if id(p) not in seen:
                seen.add(id(p))
                pnames.append(prefix + n)
                params.append(p)
        for n, b in layer.named_buffers():
            if b is not None and id(b) not in seen:
                seen.add(id(b))
                bnames.append(prefix + n)
                buffers.append(b)
    return pnames, params, bnames, buffers


@contextlib.contextmanager
def bind_state(tensors: Sequence[Tensor], values):
    """Temporarily swap each tensor's value (e.g. for traced arrays)."""
    saved = [t._value for t in tensors]
    try:
        for t, v in zip(tensors, values):
            t._value = v
        yield
    finally:
        for t, s in zip(tensors, saved):
            t._value = s


def read_values(tensors):
    return [t._value for t in tensors]
