"""Compiled-prefix capture for whole-array graph breaks (the SOT analog).

Reference: python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py:353
— when tracing hits an untraceable point (``.numpy()`` on a tracer), SOT
compiles the code BEFORE the break and resumes eager execution after it.

TPU-native equivalent, without a bytecode VM: the op stream up to the first
host read is deterministic for a fixed signature, so

1. **Record** (one eager run): every ``dispatch`` call logs its op fn, leaf
   layout, and the PROVENANCE of each tensor argument — a function input,
   a previous op's output, or a small constant. ``Tensor.numpy()`` marks
   the break.
2. **Compile**: the recorded graph up to the break is replayed symbolically
   into ONE jitted program ``(state_vals, dyn_vals) -> all prefix op
   outputs`` — XLA fuses the whole prefix.
3. **Replay** (steady state): the compiled prefix runs first; the function
   then executes eagerly, and each prefix-position dispatch is answered
   from the precomputed outputs (verified against the recording — any
   mismatch abandons replay for plain eager). Ops after the break dispatch
   normally (each still hitting the compiled eager cache).

**Training prefixes** (VERDICT r3 #7): a prefix that RECORDS GRADIENTS is
captured too — the whole prefix compiles as one ``jax.vjp`` pair (cached
exactly like the eager dispatch cache caches per-op vjps) and replay
attaches ONE tape node covering every prefix output, so ``.backward()``
through a ``.numpy()``-breaking *training* step differentiates the compiled
prefix like any other op (reference: SOT compiles training code through
breaks, jit/sot/opcode_translator/executor/opcode_executor.py:353).

**RNG prefixes** (VERDICT r4 #6): a prefix that DRAWS randomness (dropout
is the common case) is captured with the framework RNG threaded in as a
program INPUT — replay draws one fresh base key from the global Generator
per call and the compiled prefix derives every in-prefix key from it via
``random.provide_key`` (the same mechanism TrainStep uses), so the
randomness varies call to call instead of freezing at the recorded values.
The replayed draw SEQUENCE differs from eager (one base-key draw instead
of N in-prefix draws), which is distribution-equivalent, not bit-equal.

**AMP prefixes**: autocast is part of the capture — replay re-applies
``_maybe_amp_cast`` per op at trace time and the active policy fingerprint
is part of the jit cache key, so a program traced under one policy never
serves another. A policy that CHANGES mid-prefix still abandons.

Capture is abandoned — falling back to plain eager — when the prefix
never reaches a detectable break (or hits the structural cases below).
Abandon reasons are counted in :func:`capture_stats` so coverage loss is
visible.
"""
from __future__ import annotations

import contextlib
import functools
import weakref

import numpy as np
import jax

from ..core import tensor as T
from ..core import random as _random

#: observability: how many captures compiled / why captures were abandoned
_CAPTURE_STATS = {"captured": 0, "grad_captured": 0, "rng_captured": 0,
                  "amp_captured": 0, "abandoned": {}}


def capture_stats() -> dict:
    """Counters for compiled-prefix capture: successful captures (eval and
    grad-recording; rng_/amp_ count captures whose prefix drew randomness
    or ran under autocast) and per-reason abandon counts."""
    return {"captured": _CAPTURE_STATS["captured"],
            "grad_captured": _CAPTURE_STATS["grad_captured"],
            "rng_captured": _CAPTURE_STATS["rng_captured"],
            "amp_captured": _CAPTURE_STATS["amp_captured"],
            "abandoned": dict(_CAPTURE_STATS["abandoned"])}


def _count_abandon(reason):
    # fold per-op suffixes ("... in <op>" / "... (<op>)") into one bucket
    key = reason.split(" in ")[0].split(" (")[0]
    _CAPTURE_STATS["abandoned"][key] = \
        _CAPTURE_STATS["abandoned"].get(key, 0) + 1


def _classify(leaves):
    """Split dispatch leaves into layout tags + tensor values / statics."""
    layout, tvals, statics = [], [], []
    for leaf in leaves:
        if isinstance(leaf, T.Tensor):
            layout.append("D")
            tvals.append(leaf._value)
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            layout.append("D")
            tvals.append(leaf)
        else:
            layout.append("S")
            statics.append(leaf)
    return tuple(layout), tvals, statics


def _is_prng_key(v):
    """Typed jax PRNG key array (what random.next_key returns)."""
    try:
        return isinstance(v, jax.Array) and jax.dtypes.issubdtype(
            v.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


class _OpRecord:
    __slots__ = ("fn", "name", "treedef", "layout", "statics", "prov",
                 "out_meta", "out_treedef", "out_tpos", "out_others",
                 "recorded", "rng", "amp", "key_cells", "tainted")

    def __init__(self, fn, name, treedef, layout, statics, prov, out_meta,
                 out_treedef, out_tpos, out_others, recorded=False,
                 rng=False, amp=None, key_cells=()):
        self.fn = fn
        self.name = name
        self.treedef = treedef
        self.layout = layout
        self.statics = statics
        self.prov = prov          # per tensor-leaf: ("in",i)|("out",i,j)|("const",v)
        self.out_meta = out_meta  # (shape, dtype) per tensor output leaf
        self.out_treedef = out_treedef
        self.out_tpos = out_tpos      # leaf indices holding tensors
        self.out_others = out_others  # [(leaf index, python value), ...]
        self.recorded = recorded      # op recorded gradients when captured
        self.rng = rng                # op drew randomness when captured
        self.amp = amp                # autocast policy fingerprint at capture
        self.key_cells = key_cells    # fn closure cells holding PRNG keys
        self.tainted = recorded       # output depends on a trainable input


#: constants larger than this are not baked into a prefix (they may vary
#: call-to-call and full-value verification would be too costly)
_MAX_CONST = 1024


def _replay_key(key_base, op_idx, kind, j):
    """Per-op replay PRNG stream: NESTED fold_in — first the op index
    (one disjoint stream per recorded op), then a tagged in-op index
    (even = arg-position key j, odd = closure-cell key j). The old
    single-level ``fold_in(base, op_idx * 16 + j)`` collided as soon as
    an op carried more than 8 cell keys or 16 arg keys (op i's stream ran
    into op i+1's); nesting removes the arithmetic overlap entirely."""
    tag = 2 * j if kind == "arg" else 2 * j + 1
    return jax.random.fold_in(jax.random.fold_in(key_base, op_idx), tag)


def _run_records(records, input_vals, rng_key=None):
    """THE prefix execution contract: symbolically replay every recorded op
    against ``input_vals``, returning the per-op tensor-output lists. Shared
    by the compiled forward, the compiled vjp, and the double-grad fwd_fn —
    one place encodes the provenance wiring.

    ``rng_key`` (RNG-drawing prefixes): every in-prefix ``next_key()``
    derives from this traced base key, so the compiled program's
    randomness is an INPUT, not a baked constant. The amp cast mirrors
    eager dispatch's ``_maybe_amp_cast`` — replay traces run under the
    same ambient policy the cache key pins."""
    import types

    ctx = _random.provide_key(rng_key) if rng_key is not None \
        else contextlib.nullcontext()
    # ops that drew their key BEFORE dispatch (dropout closes over it /
    # passes it as an arg) get fresh keys derived from a stream disjoint
    # from provide_key's counter stream
    key_base = (jax.random.fold_in(rng_key, 0x5EED)
                if rng_key is not None else None)
    outs = []
    with ctx:
        for idx, r in enumerate(records):
            vals, si, pi = [], iter(r.statics), iter(r.prov)
            for tag in r.layout:
                if tag == "S":
                    vals.append(next(si))
                else:
                    p = next(pi)
                    if p[0] == "in":
                        vals.append(input_vals[p[1]])
                    elif p[0] == "out":
                        vals.append(outs[p[1]][p[2]])
                    elif p[0] == "rng":
                        # arg-position PRNG key: fresh per replay
                        vals.append(_replay_key(key_base, idx, "arg", p[1]))
                    else:
                        vals.append(p[1])
            vals = T._maybe_amp_cast(r.name, vals)
            a, k = jax.tree_util.tree_unflatten(r.treedef, vals)
            fn = r.fn
            if r.key_cells and key_base is not None:
                # closed-over PRNG keys (dropout's `key = next_key()`):
                # rebuild the closure with fresh derived keys
                cells = list(fn.__closure__)
                for j, ci in enumerate(r.key_cells):
                    cells[ci] = types.CellType(
                        _replay_key(key_base, idx, "cell", j))
                fn = types.FunctionType(fn.__code__, fn.__globals__,
                                        fn.__name__, fn.__defaults__,
                                        tuple(cells))
            raw = jax.tree_util.tree_leaves(fn(*a, **k))
            outs.append([raw[i] for i in r.out_tpos])
    return outs


class PrefixRecorder:
    """Installed as core.tensor._capture.recorder (thread-local) for one
    eager run."""

    def __init__(self, input_vals):
        self._prov = {}
        for i, v in enumerate(input_vals):
            self._prov[id(v)] = ("in", i)
        self._pins = list(input_vals)  # keep ids stable while recording
        self.records: list[_OpRecord] = []
        self.break_found = False
        self.aborted = None  # reason string when capture is impossible
        self.grad_recorded = False  # any prefix op recorded gradients
        self.diff_inputs = set()    # input positions feeding diff op args

    # -- dispatch hook -------------------------------------------------------
    def after_op(self, fn, name, leaves, treedef, result, recorded_grad,
                 rng_drew):
        if self.break_found or self.aborted:
            return
        from ..amp import policy_fingerprint
        amp_sig = policy_fingerprint()
        layout, tvals, statics = _classify(leaves)
        for s in statics:
            try:
                hash(s)
            except TypeError:
                # hashability is an IMMUTABILITY heuristic: a mutable
                # static (list/dict) mutated after the recording would
                # pass _matches' equality check against ITSELF and replay
                # stale values. `slice` is immutable but only hashable
                # from Python 3.12 — admit it when its components are
                # (getitem's `x[:, :n]` is all over model code; this was
                # the silent capture-killer for every prefix crossing an
                # indexing op on 3.10/3.11)
                if isinstance(s, slice):
                    try:
                        hash((s.start, s.stop, s.step))
                        continue
                    except TypeError:
                        pass
                self.aborted = f"unhashable static arg in {name}"
                return
        # PRNG keys closed over by the op fn (dropout's pre-dispatch draw):
        # replay substitutes fresh derived keys into these cells
        key_cells = []
        for ci, cell in enumerate(getattr(fn, "__closure__", None) or ()):
            try:
                if _is_prng_key(cell.cell_contents):
                    key_cells.append(ci)
            except ValueError:
                continue
        n_rng_args = 0
        tensor_leaves = [l for l in leaves
                         if isinstance(l, (T.Tensor, jax.Array, np.ndarray))]
        prov = []
        tainted = recorded_grad
        for v, leaf in zip(tvals, tensor_leaves):
            if _is_prng_key(v) and id(v) not in self._prov:
                # arg-position PRNG key: drawn fresh per call by design
                prov.append(("rng", n_rng_args))
                n_rng_args += 1
                continue
            p = self._prov.get(id(v))
            trainable = isinstance(leaf, T.Tensor) and not leaf.stop_gradient
            if trainable:
                tainted = True
            if p is None:
                if getattr(v, "size", _MAX_CONST + 1) > _MAX_CONST:
                    self.aborted = f"large unknown-provenance tensor in {name}"
                    return
                if trainable:
                    # a trainable leaf that is neither a prefix input nor a
                    # prefix intermediate would lose its gradient in replay
                    self.aborted = \
                        f"trainable leaf outside prefix inputs in {name}"
                    return
                p = ("const", np.asarray(v))
            elif p[0] == "in" and trainable and recorded_grad:
                # a trainable function input reaches a grad-RECORDING op:
                # the compiled prefix must differentiate w.r.t. it. (A
                # trainable input consumed only under no_grad must NOT
                # become a tape parent — eager leaves its .grad None, and a
                # spurious zero grad would let the optimizer apply weight
                # decay to it.)
                self.diff_inputs.add(p[1])
            elif p[0] == "out":
                producer = self.records[p[1]]
                if producer.tainted:
                    tainted = True
                if recorded_grad:
                    # the whole-prefix vjp differentiates through EVERY
                    # intermediate; eager would cut gradient flow at a
                    # no_grad producer or a detached intermediate — but
                    # ONLY if that intermediate actually depends on a
                    # trainable input (integer masks / position ids from
                    # non-trainable inputs carry no gradient either way)
                    if not producer.recorded and producer.tainted:
                        self.aborted = \
                            f"no_grad boundary inside prefix ({name})"
                        return
                    import jax.numpy as jnp
                    if producer.recorded and isinstance(leaf, T.Tensor) \
                            and leaf.stop_gradient \
                            and jnp.issubdtype(leaf._value.dtype,
                                               jnp.inexact):
                        self.aborted = \
                            f"detached intermediate in grad prefix ({name})"
                        return
            prov.append(p)
        if recorded_grad:
            self.grad_recorded = True
        out_all, out_treedef = jax.tree_util.tree_flatten(
            result, is_leaf=lambda x: isinstance(x, T.Tensor))
        out_tpos, out_vals, out_others = [], [], []
        for idx, x in enumerate(out_all):
            if isinstance(x, T.Tensor):
                out_tpos.append(idx)
                out_vals.append(x._value)
            else:
                out_others.append((idx, x))
        op_i = len(self.records)
        for j, ov in enumerate(out_vals):
            self._prov[id(ov)] = ("out", op_i, j)
            self._pins.append(ov)
        self.records.append(_OpRecord(
            fn, name, treedef, layout, tuple(statics), tuple(prov),
            tuple((tuple(ov.shape), str(ov.dtype)) for ov in out_vals),
            out_treedef, tuple(out_tpos), tuple(out_others),
            recorded=recorded_grad,
            rng=rng_drew or bool(key_cells) or n_rng_args > 0,
            amp=amp_sig, key_cells=tuple(key_cells)))
        self.records[-1].tainted = tainted

    # -- host-read hook ------------------------------------------------------
    def on_host_read(self, value):
        """Tensor.numpy()/__array__ during recording: the break point."""
        if not self.break_found and not self.aborted:
            self.break_found = True

    def build(self):
        """Compile the prefix program, or return None when capture failed."""
        if not self.aborted and self.records and \
                len({r.amp for r in self.records}) > 1:
            # the autocast policy changed INSIDE the prefix — replay traces
            # under ONE ambient policy, so a mid-prefix transition can't be
            # reproduced; fall back to eager
            self.aborted = "autocast policy changes inside prefix"
        if self.aborted or not self.break_found or not self.records:
            if self.aborted:
                _count_abandon(self.aborted)
            elif not self.break_found:
                _count_abandon("no detectable break")
            return None
        records = list(self.records)
        uses_rng = any(r.rng for r in records)
        if uses_rng:
            _CAPTURE_STATS["rng_captured"] += 1
        if any(r.amp is not None for r in records):
            _CAPTURE_STATS["amp_captured"] += 1

        def prefix_fn(input_vals, rng_key=None):
            return _run_records(records, input_vals, rng_key)

        if self.grad_recorded:
            # training prefix: ONE jax.vjp over the whole prefix, jitted —
            # the prefix analog of the eager dispatch cache's cached vjp
            # pair. Replay attaches a single tape node for every output.
            diff_idx = tuple(sorted(self.diff_inputs))

            def fwd(input_vals, rng_key=None):
                def closed(*diff_vals):
                    vv = list(input_vals)
                    for p, v in zip(diff_idx, diff_vals):
                        vv[p] = v
                    return prefix_fn(vv, rng_key)
                return jax.vjp(closed,
                               *[input_vals[p] for p in diff_idx])

            _CAPTURE_STATS["grad_captured"] += 1
            # forward-only variant compiled alongside: eval/no_grad calls on
            # this signature must not materialize the vjp residuals
            return PrefixProgram(jax.jit(fwd), records, diff_idx=diff_idx,
                                 jitted_fwd=jax.jit(prefix_fn),
                                 uses_rng=uses_rng)

        # NOTE: jax.jit is lazy — trace failures surface at the first call,
        # which PrefixProgram.run converts into _ReplayAbandoned so the
        # caller can demote to plain eager instead of crashing
        _CAPTURE_STATS["captured"] += 1
        return PrefixProgram(jax.jit(prefix_fn), records, uses_rng=uses_rng)


class _ReplayAbandoned(Exception):
    """The compiled prefix itself could not run (trace/compile failure).
    Raised BEFORE any user code executes — safe to fall back to eager."""


class PrefixProgram:
    """Steady state: one compiled prefix + positional replay of its ops.

    ``diff_idx`` non-None marks a TRAINING prefix: the jitted program is a
    ``jax.vjp`` pair over the inputs at those positions, and replay builds
    one tape node spanning every prefix output."""

    def __init__(self, jitted, records, diff_idx=None, jitted_fwd=None,
                 uses_rng=False):
        self.jitted = jitted
        self.records = records
        self.diff_idx = diff_idx
        self.jitted_fwd = jitted_fwd  # forward-only program (grad prefixes)
        self.uses_rng = uses_rng      # prefix randomness is a program input
        self.failures = 0

    @property
    def grad_capable(self):
        return self.diff_idx is not None

    def _tape_parents(self, input_tensors):
        """The diff-input Tensors, or None when this call can't rebuild the
        tape (grads off, tensors missing, or a recorded trainable frozen
        since capture — grads would be wrong)."""
        if input_tensors is None or not T.is_grad_enabled():
            return None
        parents = []
        for p in self.diff_idx:
            t = input_tensors[p] if p < len(input_tensors) else None
            if t is None or t.stop_gradient:
                return None
            parents.append(t)
        return parents or None

    def run(self, input_vals, call_fn, input_tensors=None):
        """Execute ``call_fn`` eagerly with prefix dispatches answered from
        the compiled program. Divergence mid-stream is NOT an error: every
        replayed value is provenance-verified, so the replay simply ends and
        execution continues eagerly — no re-run, no doubled side effects.
        For a training prefix, ``input_tensors`` (aligned with
        ``input_vals``; None for non-Tensor inputs) supplies the tape
        parents. Returns (result, diverged)."""
        node = None
        parents = self._tape_parents(input_tensors) if self.grad_capable \
            else None
        # RNG prefixes: ONE fresh base key per replay, drawn from (and
        # advancing) the global Generator — in-prefix keys derive from it
        # inside the compiled program, so randomness varies per call
        rng_key = _random.next_key() if self.uses_rng else None
        try:
            if parents is not None:
                outs, vjp_obj = self.jitted(input_vals, rng_key)
                node = self._make_node(outs, vjp_obj, input_vals, parents,
                                       rng_key)
            elif self.grad_capable:
                # eval / no_grad call on a training-captured signature: the
                # forward-only program — no vjp residuals materialized
                outs = self.jitted_fwd(input_vals, rng_key)
            else:
                outs = self.jitted(input_vals, rng_key)
        except Exception as e:  # trace/compile failure (jit is lazy)
            raise _ReplayAbandoned(str(e)) from e
        state = _ReplayState(self.records, outs, input_vals, node=node)
        saved = T._capture.replay
        T._capture.replay = state
        try:
            result = call_fn()
        finally:
            T._capture.replay = saved
        return result, state.diverged

    def _make_node(self, outs, vjp_obj, input_vals, parents, rng_key=None):
        """One tape node covering the whole compiled prefix: cotangents for
        every prefix output flow through the cached vjp to the diff inputs
        (the prefix analog of _dispatch_cached's per-op node). ``rng_key``
        pins THIS call's randomness for the double-grad fwd_fn."""
        flat, out_treedef = jax.tree_util.tree_flatten(outs)
        out_avals = [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in flat]
        records, diff_idx = self.records, self.diff_idx

        def fwd_fn(*diff_vals):
            vv = list(input_vals)
            for p, v in zip(diff_idx, diff_vals):
                vv[p] = v
            return _run_records(records, vv, rng_key)

        node = T.Node(functools.partial(T._bwd_call, vjp_obj), parents,
                      out_treedef, out_avals, "compiled_prefix",
                      fwd_fn=fwd_fn)
        node.outputs = [None] * len(out_avals)
        return node


class _ReplayState:
    __slots__ = ("records", "outs", "input_vals", "i", "done", "diverged",
                 "node", "_base")

    def __init__(self, records, outs, input_vals, node=None):
        self.records = records
        self.outs = outs
        self.input_vals = input_vals
        self.i = 0
        self.done = False
        self.diverged = False
        #: tape node spanning all prefix outputs (training prefix), or None
        self.node = node
        base, acc = [], 0
        for group in outs:
            base.append(acc)
            acc += len(group)
        self._base = base

    def _matches(self, r, name, leaves, treedef, record):
        if record and self.node is None:
            # replayed tensors carry no tape and this prefix compiled no
            # vjp — a grad-recording op must run eagerly (and ends the
            # replay: its outputs' provenance is gone)
            return False
        if self.node is not None and record != r.recorded:
            # grad-capable replay: each op's recording state must match the
            # capture (a frozen-since-capture or newly-trainable leaf would
            # silently change which outputs join the tape)
            return False
        layout, tvals, statics = _classify(leaves)
        if name != r.name or layout != r.layout or treedef != r.treedef \
                or tuple(statics) != r.statics:
            return False
        # PROVENANCE check: the same op name with different wiring must not
        # replay — each tensor arg must be the exact input / prior replayed
        # output / unchanged small constant the recording saw
        for v, p in zip(tvals, r.prov):
            if p[0] == "in":
                if v is not self.input_vals[p[1]]:
                    return False
            elif p[0] == "out":
                if v is not self.outs[p[1]][p[2]]:
                    return False
            elif p[0] == "rng":
                # a fresh-drawn PRNG key differs every call by design; the
                # replayed program derives its own from the base key input
                if not _is_prng_key(v):
                    return False
            elif not np.array_equal(np.asarray(v), p[1]):
                return False
        out_vals = self.outs[self.i]
        for ov, (shape, dt) in zip(out_vals, r.out_meta):
            if tuple(ov.shape) != shape or str(ov.dtype) != dt:
                return False
        return True

    def try_replay(self, fn, name, leaves, treedef, record):
        """Wrapped outputs for the next prefix op, or T._REPLAY_PASS — on
        prefix exhaustion OR divergence (verified-correct values make ending
        the replay early always safe; the op then dispatches eagerly)."""
        if self.done:
            return T._REPLAY_PASS
        if self.i >= len(self.records):
            self.done = True
            return T._REPLAY_PASS
        r = self.records[self.i]
        if not self._matches(r, name, leaves, treedef, record):
            self.done = True
            self.diverged = True
            return T._REPLAY_PASS
        out_vals = self.outs[self.i]
        base = self._base[self.i]
        self.i += 1
        # rebuild the op's exact output structure from the recording
        n = len(r.out_tpos) + len(r.out_others)
        out_leaves = [None] * n
        import jax.numpy as jnp
        for j, (idx, ov) in enumerate(zip(r.out_tpos, out_vals)):
            # only outputs of ops that RECORDED at capture time join the
            # tape — a no_grad op's output stays a constant, like eager
            diff = self.node is not None and r.recorded and \
                jnp.issubdtype(ov.dtype, jnp.inexact)
            t = T.Tensor(ov, stop_gradient=not diff)
            if diff:
                # link into the single prefix-spanning tape node
                t._node = self.node
                t._out_index = base + j
                self.node.outputs[base + j] = weakref.ref(t)
            out_leaves[idx] = t
        for idx, other in r.out_others:
            out_leaves[idx] = other
        return jax.tree_util.tree_unflatten(r.out_treedef, out_leaves)
