"""Compiled-prefix capture for whole-array graph breaks (the SOT analog).

Reference: python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py:353
— when tracing hits an untraceable point (``.numpy()`` on a tracer), SOT
compiles the code BEFORE the break and resumes eager execution after it.

TPU-native equivalent, without a bytecode VM: the op stream up to the first
host read is deterministic for a fixed signature, so

1. **Record** (one eager run): every ``dispatch`` call logs its op fn, leaf
   layout, and the PROVENANCE of each tensor argument — a function input,
   a previous op's output, or a small constant. ``Tensor.numpy()`` marks
   the break.
2. **Compile**: the recorded graph up to the break is replayed symbolically
   into ONE jitted program ``(state_vals, dyn_vals) -> all prefix op
   outputs`` — XLA fuses the whole prefix.
3. **Replay** (steady state): the compiled prefix runs first; the function
   then executes eagerly, and each prefix-position dispatch is answered
   from the precomputed outputs (verified against the recording — any
   mismatch abandons replay for plain eager). Ops after the break dispatch
   normally (each still hitting the compiled eager cache).

Capture is abandoned — falling back to plain eager — when the prefix draws
RNG (a compiled replay would freeze the randomness), records gradients
(replayed values carry no tape), runs under AMP autocast, or never reaches
a detectable break.
"""
from __future__ import annotations

import numpy as np
import jax

from ..core import tensor as T
from ..core import random as _random


def _classify(leaves):
    """Split dispatch leaves into layout tags + tensor values / statics."""
    layout, tvals, statics = [], [], []
    for leaf in leaves:
        if isinstance(leaf, T.Tensor):
            layout.append("D")
            tvals.append(leaf._value)
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            layout.append("D")
            tvals.append(leaf)
        else:
            layout.append("S")
            statics.append(leaf)
    return tuple(layout), tvals, statics


class _OpRecord:
    __slots__ = ("fn", "name", "treedef", "layout", "statics", "prov",
                 "out_meta", "out_treedef", "out_tpos", "out_others")

    def __init__(self, fn, name, treedef, layout, statics, prov, out_meta,
                 out_treedef, out_tpos, out_others):
        self.fn = fn
        self.name = name
        self.treedef = treedef
        self.layout = layout
        self.statics = statics
        self.prov = prov          # per tensor-leaf: ("in",i)|("out",i,j)|("const",v)
        self.out_meta = out_meta  # (shape, dtype) per tensor output leaf
        self.out_treedef = out_treedef
        self.out_tpos = out_tpos      # leaf indices holding tensors
        self.out_others = out_others  # [(leaf index, python value), ...]


#: constants larger than this are not baked into a prefix (they may vary
#: call-to-call and full-value verification would be too costly)
_MAX_CONST = 1024


class PrefixRecorder:
    """Installed as core.tensor._capture.recorder (thread-local) for one
    eager run."""

    def __init__(self, input_vals):
        self._prov = {}
        for i, v in enumerate(input_vals):
            self._prov[id(v)] = ("in", i)
        self._pins = list(input_vals)  # keep ids stable while recording
        self.records: list[_OpRecord] = []
        self.break_found = False
        self.aborted = None  # reason string when capture is impossible

    # -- dispatch hook -------------------------------------------------------
    def after_op(self, fn, name, leaves, treedef, result, recorded_grad,
                 rng_drew):
        if self.break_found or self.aborted:
            return
        if recorded_grad:
            self.aborted = "prefix records gradients"
            return
        if rng_drew:
            self.aborted = "prefix draws RNG"
            return
        from ..amp import _state as _amp_state
        if getattr(_amp_state, "enabled", False):
            self.aborted = "prefix under AMP autocast"
            return
        layout, tvals, statics = _classify(leaves)
        try:
            for s in statics:
                hash(s)
        except TypeError:
            self.aborted = f"unhashable static arg in {name}"
            return
        prov = []
        for v in tvals:
            p = self._prov.get(id(v))
            if p is None:
                if getattr(v, "size", _MAX_CONST + 1) > _MAX_CONST:
                    self.aborted = f"large unknown-provenance tensor in {name}"
                    return
                p = ("const", np.asarray(v))
            prov.append(p)
        out_all, out_treedef = jax.tree_util.tree_flatten(
            result, is_leaf=lambda x: isinstance(x, T.Tensor))
        out_tpos, out_vals, out_others = [], [], []
        for idx, x in enumerate(out_all):
            if isinstance(x, T.Tensor):
                out_tpos.append(idx)
                out_vals.append(x._value)
            else:
                out_others.append((idx, x))
        op_i = len(self.records)
        for j, ov in enumerate(out_vals):
            self._prov[id(ov)] = ("out", op_i, j)
            self._pins.append(ov)
        self.records.append(_OpRecord(
            fn, name, treedef, layout, tuple(statics), tuple(prov),
            tuple((tuple(ov.shape), str(ov.dtype)) for ov in out_vals),
            out_treedef, tuple(out_tpos), tuple(out_others)))

    # -- host-read hook ------------------------------------------------------
    def on_host_read(self, value):
        """Tensor.numpy()/__array__ during recording: the break point."""
        if not self.break_found and not self.aborted:
            self.break_found = True

    def build(self):
        """Compile the prefix program, or return None when capture failed."""
        if self.aborted or not self.break_found or not self.records:
            return None
        records = list(self.records)

        def prefix_fn(input_vals):
            outs = []
            for r in records:
                vals, si, pi = [], iter(r.statics), iter(r.prov)
                for tag in r.layout:
                    if tag == "S":
                        vals.append(next(si))
                    else:
                        p = next(pi)
                        if p[0] == "in":
                            vals.append(input_vals[p[1]])
                        elif p[0] == "out":
                            vals.append(outs[p[1]][p[2]])
                        else:
                            vals.append(p[1])
                a, k = jax.tree_util.tree_unflatten(r.treedef, vals)
                out = r.fn(*a, **k)  # raw jax values (dispatch fn contract)
                raw = jax.tree_util.tree_leaves(out)
                outs.append([raw[i] for i in r.out_tpos])
            return outs

        # NOTE: jax.jit is lazy — trace failures surface at the first call,
        # which PrefixProgram.run converts into _ReplayAbandoned so the
        # caller can demote to plain eager instead of crashing
        return PrefixProgram(jax.jit(prefix_fn), records)


class _ReplayAbandoned(Exception):
    """The compiled prefix itself could not run (trace/compile failure).
    Raised BEFORE any user code executes — safe to fall back to eager."""


class PrefixProgram:
    """Steady state: one compiled prefix + positional replay of its ops."""

    def __init__(self, jitted, records):
        self.jitted = jitted
        self.records = records
        self.failures = 0

    def run(self, input_vals, call_fn):
        """Execute ``call_fn`` eagerly with prefix dispatches answered from
        the compiled program. Divergence mid-stream is NOT an error: every
        replayed value is provenance-verified, so the replay simply ends and
        execution continues eagerly — no re-run, no doubled side effects.
        Returns (result, diverged)."""
        try:
            outs = self.jitted(input_vals)
        except Exception as e:  # trace/compile failure (jit is lazy)
            raise _ReplayAbandoned(str(e)) from e
        state = _ReplayState(self.records, outs, input_vals)
        saved = T._capture.replay
        T._capture.replay = state
        try:
            result = call_fn()
        finally:
            T._capture.replay = saved
        return result, state.diverged


class _ReplayState:
    __slots__ = ("records", "outs", "input_vals", "i", "done", "diverged")

    def __init__(self, records, outs, input_vals):
        self.records = records
        self.outs = outs
        self.input_vals = input_vals
        self.i = 0
        self.done = False
        self.diverged = False

    def _matches(self, r, name, leaves, treedef, record):
        if record:
            # replayed tensors carry no tape — a grad-recording op must run
            # eagerly (and ends the replay: its outputs' provenance is gone)
            return False
        layout, tvals, statics = _classify(leaves)
        if name != r.name or layout != r.layout or treedef != r.treedef \
                or tuple(statics) != r.statics:
            return False
        # PROVENANCE check: the same op name with different wiring must not
        # replay — each tensor arg must be the exact input / prior replayed
        # output / unchanged small constant the recording saw
        for v, p in zip(tvals, r.prov):
            if p[0] == "in":
                if v is not self.input_vals[p[1]]:
                    return False
            elif p[0] == "out":
                if v is not self.outs[p[1]][p[2]]:
                    return False
            elif not np.array_equal(np.asarray(v), p[1]):
                return False
        out_vals = self.outs[self.i]
        for ov, (shape, dt) in zip(out_vals, r.out_meta):
            if tuple(ov.shape) != shape or str(ov.dtype) != dt:
                return False
        return True

    def try_replay(self, fn, name, leaves, treedef, record):
        """Wrapped outputs for the next prefix op, or T._REPLAY_PASS — on
        prefix exhaustion OR divergence (verified-correct values make ending
        the replay early always safe; the op then dispatches eagerly)."""
        if self.done:
            return T._REPLAY_PASS
        if self.i >= len(self.records):
            self.done = True
            return T._REPLAY_PASS
        r = self.records[self.i]
        if not self._matches(r, name, leaves, treedef, record):
            self.done = True
            self.diverged = True
            return T._REPLAY_PASS
        out_vals = self.outs[self.i]
        self.i += 1
        # rebuild the op's exact output structure from the recording
        n = len(r.out_tpos) + len(r.out_others)
        out_leaves = [None] * n
        for idx, ov in zip(r.out_tpos, out_vals):
            out_leaves[idx] = T.Tensor(ov)
        for idx, other in r.out_others:
            out_leaves[idx] = other
        return jax.tree_util.tree_unflatten(r.out_treedef, out_leaves)
