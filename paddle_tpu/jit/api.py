"""paddle.jit.to_static analog + compiled train step.

Reference: python/paddle/jit/api.py:197 (to_static), jit/sot (bytecode capture),
pir_partial_program (graph into executor). TPU-native: `to_static` wraps a function or
Layer so calls trace once through jax.jit (XLA is the executor; the jaxpr is the IR);
parameters/buffers enter as jit inputs so weight updates don't recompile, and buffer
mutations (BN running stats) round-trip as outputs. `TrainStep` fuses
forward+backward+optimizer into ONE compiled program with buffer donation — the analog
of the reference's Plan/Job executor running a whole iteration.
"""
from __future__ import annotations

import functools
import os
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, functional_mode, no_grad
from ..core import random as _random
from ..nn.layer_base import Layer
from .functional_call import collect_state, bind_state, read_values


def _find_layers(fn, args):
    """Discover Layer instances a callable touches: self, args, and closure cells
    (the analog of SOT guarding on the frame's free variables)."""
    layers = []

    def add(obj):
        if isinstance(obj, Layer) and all(obj is not l for l in layers):
            layers.append(obj)
        elif isinstance(obj, (list, tuple)):
            for item in obj:
                add(item)

    def scan_callable(f):
        add(f)
        if hasattr(f, "__self__"):
            add(f.__self__)
        for cell in getattr(f, "__closure__", None) or ():
            try:
                add(cell.cell_contents)
            except ValueError:
                continue
        for d in getattr(f, "__defaults__", None) or ():
            add(d)
        for d in (getattr(f, "__kwdefaults__", None) or {}).values():
            add(d)

    scan_callable(fn)
    if isinstance(fn, functools.partial):
        add(list(fn.args))
        add(list(fn.keywords.values()))
        scan_callable(fn.func)
    for a in jax.tree_util.tree_leaves(args, is_leaf=lambda x: isinstance(x, Layer)):
        add(a)
    return layers


def _split_leaves(tree):
    """Split pytree into (dynamic tensor/array leaves, static structure key)."""
    leaves, treedef = jax.tree_util.tree_flatten(
        tree, is_leaf=lambda x: isinstance(x, Tensor))
    dyn, static_key, layout = [], [], []
    for leaf in leaves:
        if isinstance(leaf, Tensor):
            dyn.append(leaf._value)
            layout.append("T")
        elif isinstance(leaf, (jax.Array, np.ndarray)):
            dyn.append(jnp.asarray(leaf))
            layout.append("A")
        else:
            static_key.append(leaf)
            layout.append("S")
    return dyn, tuple(static_key), tuple(layout), treedef


_EAGER_FALLBACK = object()  # cache sentinel: this signature runs eagerly


class _PrefixEntry:
    """Cache entry: compiled-prefix capture after a whole-array graph break
    (see jit/prefix_capture.py)."""

    __slots__ = ("program",)

    def __init__(self, program):
        self.program = program


class _Specializer:
    """Per-signature state after a data-dependent graph break (reference:
    jit/sot opcode_executor.py:353 — SOT keeps the compiled prefix and guards
    on the concretized values; torch.compile splits frames the same way).

    TPU-native version: *speculative specialization with post-validation*.
    On a break, the call runs eagerly once while every concretized scalar
    (bool(t)/int(t)/t.item()) is recorded — that's the branch profile. A
    program specialized to the profile is then compiled, with the concretized
    scalars as extra outputs (the guards). Later calls run the compiled
    program and compare the guard outputs to the profile: match -> compiled
    result stands (the hot branch never leaves XLA); mismatch -> results are
    discarded, the call re-runs eagerly, and the new profile gets its own
    compiled program. Safe because traced programs are pure: buffer updates
    are applied only after validation.
    """

    def __init__(self):
        self.programs = {}     # profile tuple -> jitted specialized program
        self.last_profile = None
        self.failed = False    # a specialized trace also broke -> plain eager


class StaticFunction:
    """Traced+compiled callable with a guard cache keyed on static structure."""

    def __init__(self, function, input_spec=None, full_graph=True, backend=None):
        self._fn = function
        self._cache = {}
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__", "__qualname__"),
                                 updated=())

    @property
    def function(self):
        return self._fn

    def __get__(self, instance, owner):
        if instance is None:
            return self
        return functools.partial(self.__call__, instance)

    def _make_body(self, static_key, layout, treedef, params, buffers):
        fn = self._fn
        state_tensors = params + buffers

        def compiled(state_vals, dyn_vals, rng_key):
            # rebuild args with traced leaves
            it = iter(dyn_vals)
            statics = iter(static_key)
            leaves = []
            for tag in layout:
                if tag == "S":
                    leaves.append(next(statics))
                elif tag == "T":
                    leaves.append(Tensor(next(it)))
                else:
                    leaves.append(next(it))
            a, k = jax.tree_util.tree_unflatten(treedef, leaves)
            with functional_mode(), bind_state(state_tensors, state_vals), \
                    _random.provide_key(rng_key):
                out = fn(*a, **k)
                new_buf_vals = [b._value for b in buffers]
            out_vals = jax.tree_util.tree_map(
                lambda t: t._value if isinstance(t, Tensor) else t, out,
                is_leaf=lambda x: isinstance(x, Tensor))
            return out_vals, new_buf_vals

        return compiled

    #: max distinct branch profiles compiled per signature before giving up
    #: (the torch.compile recompile_limit analog)
    _MAX_PROFILES = 8

    #: canonical stand-in for NaN guard values in profile keys: NaN never
    #: compares (or, per-instance, hashes) equal to itself, so raw-NaN tuples
    #: would miss both _profiles_match and the programs-dict lookups,
    #: recompiling an identical program per call until the cap. The RAW
    #: recorded values (real NaNs) still feed the specialized trace.
    _NAN = object()

    @classmethod
    def _canon_profile(cls, values):
        return tuple(cls._NAN if isinstance(v, float) and v != v else v
                     for v in values)

    @staticmethod
    def _profiles_match(observed, profile):
        # EXACT equality, floats included: a spurious mismatch merely costs an
        # eager re-profile, but any tolerance can validate a guard that sits
        # across a python comparison threshold and commit the wrong branch.
        # (Both sides are canonical profiles — NaN already collapsed to _NAN.)
        return len(observed) == len(profile) and \
            all(o == p for o, p in zip(observed, profile))

    def _call_specialized(self, spec, body, args, kwargs, state_vals, dyn,
                          buffers):
        from ..core.tensor import ConcretizeScope, concretize_scope
        # try the last profile's program; on guard divergence, the observed
        # guards name the true profile — if it's already compiled, run it and
        # validate ITS guards (alternating-branch workloads stay compiled)
        candidate = spec.last_profile
        tried = set()
        while not spec.failed and candidate is not None \
                and candidate not in tried:
            tried.add(candidate)
            prog = spec.programs.get(candidate)
            if prog is None:
                break
            try:
                out_vals, new_buf_vals, guards = prog(
                    state_vals, dyn, _random.next_key())
                observed = self._canon_profile(
                    np.asarray(g).item() for g in guards)
            except (jax.errors.ConcretizationTypeError,
                    jax.errors.TracerIntegerConversionError,
                    jax.errors.TracerArrayConversionError,
                    jax.errors.NonConcreteBooleanIndexError,
                    IndexError):
                # the specialized trace itself broke (.numpy() on a tracer,
                # profile under-recorded, ...) — plain eager from now on
                spec.failed = True
                return self._fn(*args, **kwargs)
            if self._profiles_match(observed, candidate):
                spec.last_profile = candidate
                for b, nv in zip(buffers, new_buf_vals):
                    b._value = nv
                return jax.tree_util.tree_map(
                    lambda v: Tensor(v) if isinstance(v, jax.Array)
                    else v, out_vals)
            # speculative results discarded (pure program — nothing was
            # committed); the observed prefix points at the true profile
            candidate = observed if observed in spec.programs else None
        if spec.failed:
            return self._fn(*args, **kwargs)

        # eager profiling run: record every concretized scalar
        scope = ConcretizeScope()
        with concretize_scope(scope):
            result = self._fn(*args, **kwargs)
        profile_raw = tuple(scope.recorded)
        profile = self._canon_profile(profile_raw)
        spec.last_profile = profile
        if profile not in spec.programs:
            if len(spec.programs) >= self._MAX_PROFILES:
                import warnings
                warnings.warn(
                    f"to_static: {getattr(self._fn, '__name__', '?')} exceeded "
                    f"{self._MAX_PROFILES} branch profiles (data-dependent "
                    f"value with many distinct outcomes); running eagerly",
                    RuntimeWarning, stacklevel=2)
                spec.failed = True
                return result
            profile_list = list(profile_raw)

            def specialized(state_vals, dyn_vals, rng_key):
                sc = ConcretizeScope(feed=profile_list)
                with concretize_scope(sc):
                    out_vals, new_bufs = body(state_vals, dyn_vals, rng_key)
                return out_vals, new_bufs, tuple(sc.guards)

            spec.programs[profile] = jax.jit(specialized)
        return result

    def __call__(self, *args, **kwargs):
        layers = _find_layers(self._fn, args)
        pnames, params, bnames, buffers = collect_state(layers)
        dyn, static_key, layout, treedef = _split_leaves((args, kwargs))
        # the autocast policy is part of the program identity: a body (or
        # captured prefix) traced under one policy bakes its casts in and
        # must not serve calls under another
        from ..amp import policy_fingerprint
        key = (static_key, layout, treedef, tuple(id(p) for p in params),
               policy_fingerprint())

        entry = self._cache.get(key)
        if entry is None:
            entry = self._cache[key] = jax.jit(
                self._make_body(static_key, layout, treedef, params, buffers))

        if entry is _EAGER_FALLBACK:
            return self._fn(*args, **kwargs)

        state_vals = read_values(params) + read_values(buffers)
        if isinstance(entry, _Specializer):
            body = self._make_body(static_key, layout, treedef, params,
                                   buffers)
            return self._call_specialized(entry, body, args, kwargs,
                                          state_vals, dyn, buffers)

        if isinstance(entry, _PrefixEntry):
            from .prefix_capture import _ReplayAbandoned
            from ..core.tensor import is_grad_enabled
            grads_will_record = is_grad_enabled() and (
                any(not p.stop_gradient for p in params)
                or any(isinstance(a, Tensor) and not a.stop_gradient
                       for a in jax.tree_util.tree_leaves(
                           (args, kwargs),
                           is_leaf=lambda x: isinstance(x, Tensor))))
            # grads will record but the prefix compiled no vjp (captured
            # under no-grad): run plain eager WITHOUT executing the compiled
            # prefix and WITHOUT counting a divergence (train/eval
            # alternation must not demote the eval-path capture). A
            # grad-capable prefix replays with a tape node instead.
            if grads_will_record and not entry.program.grad_capable:
                return self._fn(*args, **kwargs)
            # input tensors aligned with state_vals + dyn (None for raw
            # arrays) — the training prefix's tape parents; only grad-capable
            # programs consume them, so eval prefixes skip the tree walk
            input_tensors = None
            if entry.program.grad_capable:
                input_tensors = list(params) + list(buffers) + [
                    leaf if isinstance(leaf, Tensor) else None
                    for leaf in jax.tree_util.tree_leaves(
                        (args, kwargs),
                        is_leaf=lambda x: isinstance(x, Tensor))
                    if isinstance(leaf, (Tensor, jax.Array, np.ndarray))]
            try:
                result, diverged = entry.program.run(
                    list(state_vals) + list(dyn),
                    lambda: self._fn(*args, **kwargs),
                    input_tensors=input_tensors)
            except _ReplayAbandoned:
                # the prefix program itself failed to trace/run — raised
                # BEFORE any user code, so a plain eager call is safe
                self._cache[key] = _EAGER_FALLBACK
                return self._fn(*args, **kwargs)
            if diverged:
                # result is still correct (replayed values are provenance-
                # verified; the diverged tail ran eagerly) — but repeated
                # divergence means the prefix isn't stable for this fn
                entry.program.failures += 1
                if entry.program.failures >= 2:
                    self._cache[key] = _EAGER_FALLBACK
            return result

        rng_key = _random.next_key()
        try:
            out_vals, new_buf_vals = entry(state_vals, dyn, rng_key)
        except (jax.errors.TracerArrayConversionError,
                jax.errors.NonConcreteBooleanIndexError) as e:
            # whole-array concretization (.numpy() on a tracer, boolean mask
            # indexing): no scalar profile can fix this wholesale — but the
            # ops BEFORE the break are compilable. SOT-style prefix capture:
            # one eager recording run; when a clean prefix exists (no RNG /
            # grads / AMP in it), later calls run it as ONE compiled program
            # and resume eager at the break (reference:
            # jit/sot/opcode_translator/executor/opcode_executor.py:353).
            import warnings
            from ..core import tensor as _tensor_mod
            from .prefix_capture import PrefixRecorder
            recorder = PrefixRecorder(list(state_vals) + list(dyn))
            saved_rec = _tensor_mod._capture.recorder
            _tensor_mod._capture.recorder = recorder
            try:
                result = self._fn(*args, **kwargs)
            finally:
                _tensor_mod._capture.recorder = saved_rec
            program = recorder.build()
            if program is not None:
                warnings.warn(
                    f"to_static: graph break in "
                    f"{getattr(self._fn, '__name__', '?')} "
                    f"({type(e).__name__}); compiled a "
                    f"{len(program.records)}-op prefix, eager after the "
                    f"break", RuntimeWarning, stacklevel=2)
                self._cache[key] = _PrefixEntry(program)
            else:
                warnings.warn(
                    f"to_static: graph break in "
                    f"{getattr(self._fn, '__name__', '?')} "
                    f"({type(e).__name__}; "
                    f"{recorder.aborted or 'no capturable prefix'}); this "
                    f"call signature now runs eagerly",
                    RuntimeWarning, stacklevel=2)
                self._cache[key] = _EAGER_FALLBACK
            return result
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerIntegerConversionError) as e:
            # NOTE: in this jax version only TracerBoolConversionError is a
            # ConcretizationTypeError subclass — integer conversion must be
            # listed separately.
            # Data-dependent SCALAR control flow: specialize per branch
            # profile instead of abandoning compilation (reference: jit/sot
            # guards on the concretized value, opcode_executor.py:353).
            import warnings
            warnings.warn(
                f"to_static: data-dependent control flow in "
                f"{getattr(self._fn, '__name__', '?')} ({type(e).__name__}); "
                f"specializing per branch profile with guard validation",
                RuntimeWarning, stacklevel=2)
            spec = self._cache[key] = _Specializer()
            body = self._make_body(static_key, layout, treedef, params,
                                   buffers)
            return self._call_specialized(spec, body, args, kwargs,
                                          state_vals, dyn, buffers)
        for b, nv in zip(buffers, new_buf_vals):
            b._value = nv
        return jax.tree_util.tree_map(
            lambda v: Tensor(v) if isinstance(v, jax.Array) else v, out_vals)

    def concrete_program_specify_input_spec(self, *a, **k):  # parity shim
        return None


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, **kwargs):
    """paddle.jit.to_static — decorator or call-form."""
    def deco(fn):
        if isinstance(fn, Layer):
            fn.forward = StaticFunction(fn.forward.__func__.__get__(fn, type(fn))
                                        if hasattr(fn.forward, "__func__") else fn.forward,
                                        input_spec)
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    return fn



def _make_loss_of(model, loss_fn, params, frozen, buffers, static_key, layout,
                  treedef):
    """Build the pure loss closure shared by the single-step and
    gradient-accumulation paths: re-interleaves dynamic/static batch leaves,
    binds param/buffer values, and captures updated buffers as aux."""

    def loss_of(pv, frozen_vals, buf_vals, rng_key, dyn_vals):
        it = iter(dyn_vals)
        statics = iter(static_key)
        leaves = []
        for tag in layout:
            if tag == "S":
                leaves.append(next(statics))
            elif tag == "T":
                leaves.append(Tensor(next(it)))
            else:
                leaves.append(next(it))
        (b,) = (jax.tree_util.tree_unflatten(treedef, leaves),)
        with functional_mode(), \
                bind_state(params + frozen + buffers,
                           list(pv) + list(frozen_vals) + list(buf_vals)), \
                _random.provide_key(rng_key):
            loss = loss_fn(model, *b)
            new_bufs = [bf._value for bf in buffers]
        return loss._value, new_bufs

    return loss_of


class TrainStep:
    """One fused compiled training iteration: fwd + bwd + optimizer + buffer updates.

    loss_fn: (model, *batch) -> scalar loss Tensor (pure w.r.t. our op library).
    Donation: parameter/slot buffers are donated so param memory is updated in place
    (no 2x weight footprint) — the analog of the reference executor's inplace pass.
    """

    def __init__(self, model: Layer, loss_fn: Callable, optimizer, donate=True,
                 accumulate_steps=1):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self._cache = {}
        pnames, params, bnames, buffers = collect_state(model)
        self.params = [p for p in params if not p.stop_gradient]
        self.frozen = [p for p in params if p.stop_gradient]
        self.buffers = buffers
        self.donate = donate
        # gradient accumulation (reference: gradient_merge pass /
        # fleet accumulate_steps): K-1 grad-only microsteps into fp32
        # accumulators, optimizer-state traffic only on the K-th
        self.accumulate_steps = int(accumulate_steps)
        self._acc = None
        self._acc_placements = None
        self._acc_count = 0
        self._grad_cache = {}
        self._update_fn = None
        optimizer._ensure_slots(self.params)

    def __call__(self, *batch):
        if self.accumulate_steps > 1:
            return self._call_accumulate(*batch)
        opt = self.optimizer
        dyn, static_key, layout, treedef = _split_leaves(batch)
        from ..core.flags import flag_value
        key = (static_key, layout, treedef,
               tuple((tuple(v.shape), str(v.dtype)) for v in dyn),
               bool(flag_value("use_fused_adamw")),
               bool(flag_value("adamw_stochastic_rounding")))

        if key not in self._cache:
            self._cache[key] = self._build_step_jit(static_key, layout,
                                                    treedef)

        param_vals = read_values(self.params)
        slot_vals = [opt._slots[id(p)] for p in self.params]
        buf_vals = read_values(self.buffers)
        frozen_vals = read_values(self.frozen)
        opt._step_count += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step_i = jnp.asarray(opt._step_count, jnp.int32)
        rng_key = _random.next_key()

        loss_val, new_pv, new_slots, new_bufs = self._cache[key](
            param_vals, slot_vals, buf_vals, frozen_vals, lr, step_i, rng_key, dyn)
        for p, nv in zip(self.params, new_pv):
            p._value = nv
        for p, ns in zip(self.params, new_slots):
            opt._slots[id(p)] = ns
        for b, nv in zip(self.buffers, new_bufs):
            b._value = nv
        return Tensor(loss_val)

    def _build_step_jit(self, static_key, layout, treedef):
        """The fused fwd+bwd+update program for one batch signature."""
        opt = self.optimizer
        decay_flags = tuple(bool(opt._decay_mask(p)) for p in self.params)
        loss_of_full = _make_loss_of(self.model, self.loss_fn, self.params,
                                     self.frozen, self.buffers, static_key,
                                     layout, treedef)

        def step_fn(param_vals, slot_vals, buf_vals, frozen_vals, lr, step_i,
                    rng_key, dyn_vals):
            def loss_of(pv):
                return loss_of_full(pv, frozen_vals, buf_vals, rng_key,
                                    dyn_vals)

            (loss_val, new_bufs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            new_pv, new_slots = opt.apply_updates(
                param_vals, grads, slot_vals, lr, step_i, decay_flags)
            return loss_val, new_pv, new_slots, new_bufs

        donate = (0, 1, 2) if self.donate else ()
        return jax.jit(step_fn, donate_argnums=donate)

    def aot_compile(self, *batch):
        """AOT-compile the train step program(s) WITHOUT executing them.

        Works on a LazyGuard-abstract model: parameter, slot, and batch
        leaves may be ``jax.ShapeDtypeStruct``s (with shardings attached), so
        a model too large to materialize on one host can still be compiled,
        partitioned, and memory-checked on a virtual mesh.

        Returns the jax ``Compiled`` object (``memory_analysis()``,
        ``as_text()``) for the fused single-step program; with
        ``accumulate_steps > 1`` returns ``(microstep, update)`` Compileds —
        the microstep's arguments include the persistent fp32 accumulators
        and the update's include the optimizer slots, so a memory verdict
        must consider both. Reference analog: the static executor's
        build-program + memory planning pass, run compile-only."""
        import jax.tree_util as jtu
        opt = self.optimizer
        # the documented contract admits bare ShapeDtypeStruct batch leaves;
        # _split_leaves would classify those as static — wrap them as Tensors
        batch = jtu.tree_map(
            lambda x: Tensor(x) if isinstance(x, jax.ShapeDtypeStruct) else x,
            batch, is_leaf=lambda x: isinstance(x, (Tensor,
                                                    jax.ShapeDtypeStruct)))
        dyn, static_key, layout, treedef = _split_leaves(batch)
        param_vals = read_values(self.params)
        buf_vals = read_values(self.buffers)
        frozen_vals = read_values(self.frozen)
        rng_key = jax.eval_shape(lambda: jax.random.key(0))

        if self.accumulate_steps > 1:
            placements = tuple(self._acc_shardings())
            acc_avals = self._acc_avals(placements)
            grad_jit = self._build_grad_jit(static_key, layout, treedef,
                                            placements)
            grad_compiled = grad_jit.lower(param_vals, acc_avals, buf_vals,
                                           frozen_vals, rng_key,
                                           dyn).compile()
            slot_vals = [opt._slots[id(p)] for p in self.params]
            update_jit = self._build_update_jit(placements)
            update_compiled = update_jit.lower(
                param_vals, slot_vals, acc_avals,
                jnp.asarray(0.0, jnp.float32),
                jnp.asarray(1, jnp.int32)).compile()
            return grad_compiled, update_compiled

        jitted = self._build_step_jit(static_key, layout, treedef)
        # share the jit with __call__'s cache: a later real step with the
        # same signature reuses this trace instead of recompiling
        from ..core.flags import flag_value
        key = (static_key, layout, treedef,
               tuple((tuple(v.shape), str(v.dtype)) for v in dyn),
               bool(flag_value("use_fused_adamw")),
               bool(flag_value("adamw_stochastic_rounding")))
        self._cache.setdefault(key, jitted)
        slot_vals = [opt._slots[id(p)] for p in self.params]
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        step_i = jnp.asarray(1, jnp.int32)
        return jitted.lower(param_vals, slot_vals, buf_vals, frozen_vals,
                            lr, step_i, rng_key, dyn).compile()

    def _build_grad_jit(self, static_key, layout, treedef, placements):
        """The accumulation MICROSTEP program: fwd+bwd, grads added into the
        persistent fp32 accumulators (ZeRO-2: constrained into 1/N shards,
        reduce-scattering the dp reduction straight into the shard)."""
        loss_of_full = _make_loss_of(self.model, self.loss_fn, self.params,
                                     self.frozen, self.buffers, static_key,
                                     layout, treedef)
        acc_shardings = placements

        def grad_fn(param_vals, acc_vals, buf_vals, frozen_vals, rng_key,
                    dyn_vals):
            def loss_of(pv):
                return loss_of_full(pv, frozen_vals, buf_vals, rng_key,
                                    dyn_vals)

            (loss_val, new_bufs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            new_acc = []
            for a, g, sh in zip(acc_vals, grads, acc_shardings):
                g = g.astype(jnp.float32)
                if sh is not None:
                    if sh.flat:
                        # flat-pad storage: accumulate in the 1-D padded
                        # stored form so the buffer shards at 1/N
                        g = jnp.pad(jnp.ravel(g), (0, sh.pad_to - g.size))
                    g = jax.lax.with_sharding_constraint(g, sh.sharding)
                new_acc.append(a + g)
            return loss_val, new_acc, new_bufs

        # acc buffers are internal (never user-visible) — always donated
        return jax.jit(grad_fn, donate_argnums=(1,))

    def _build_update_jit(self, placements):
        """The accumulation-boundary UPDATE program: optimizer step on the
        accumulated mean gradient."""
        opt = self.optimizer
        decay_flags = tuple(bool(opt._decay_mask(p)) for p in self.params)
        K = self.accumulate_steps
        shapes = tuple(tuple(p.shape) for p in self.params)

        def update_fn(param_vals, slot_vals, acc_vals, lr, step_i):
            # keep the fp32 mean — both the generic multi-precision path
            # and the fused kernel upcast anyway, so downcasting here
            # would only discard the accumulated precision. Flat-stored
            # accumulators are restored to the param's shape first:
            # apply_updates resolves its own plans and must never be
            # handed grads in a storage form those plans didn't choose.
            grads = []
            for a, sh, shp in zip(acc_vals, placements, shapes):
                if sh is not None and sh.flat:
                    size = 1
                    for s in shp:
                        size *= s
                    a = jnp.reshape(a[:size], shp)
                grads.append(a / K)
            return opt.apply_updates(param_vals, grads, slot_vals, lr,
                                     step_i, decay_flags)

        donate = (0, 1, 2) if self.donate else (2,)
        return jax.jit(update_fn, donate_argnums=donate)

    def _acc_avals(self, placements):
        """Abstract accumulator buffers matching ``placements``."""
        out = []
        for p, sh in zip(self.params, placements):
            if sh is None:
                out.append(jax.ShapeDtypeStruct(tuple(p.shape), jnp.float32))
            else:
                shape = (sh.pad_to,) if sh.flat else tuple(p.shape)
                out.append(jax.ShapeDtypeStruct(shape, jnp.float32,
                                                sharding=sh.sharding))
        return out

    def _acc_shardings(self):
        """Per-param placement for grad accumulators: the ZeRO-2+ wrapper's
        AccPlacement when present (keyed by the param object), else the
        PARAM's own sharding — under TP, a grad has the param's placement,
        and a replicated fp32 accumulator would cost full bytes per device
        (27 GB at 7B scale). None = keep replicated."""
        from jax.sharding import NamedSharding
        from ..distributed.fleet.sharding_optimizer import AccPlacement
        placement = getattr(self.optimizer, "_grad_placement", None)
        out = []
        for p in self.params:
            sh = placement(p) if placement is not None else None
            if sh is None:
                psh = getattr(p._value, "sharding", None)
                if isinstance(psh, NamedSharding) and psh.spec is not None \
                        and any(s is not None for s in tuple(psh.spec)):
                    sh = AccPlacement(psh, False, 0)
            out.append(sh)
        return out

    # -- gradient-accumulation path ------------------------------------------
    def _call_accumulate(self, *batch):
        opt = self.optimizer
        dyn, static_key, layout, treedef = _split_leaves(batch)

        # accumulator placements are resolved ONCE per accumulation cycle and
        # frozen; the grad/update programs are keyed on them, so a sharding-
        # plan change between cycles recompiles instead of reusing a closure
        # baked for the old placements against new-shape accumulators
        if self._acc is None:
            self._acc_placements = tuple(self._acc_shardings())
            self._acc = []
            for p, sh in zip(self.params, self._acc_placements):
                if sh is None:
                    self._acc.append(jnp.zeros(p.shape, jnp.float32))
                else:
                    shape = (sh.pad_to,) if sh.flat else tuple(p.shape)
                    self._acc.append(jax.device_put(
                        jnp.zeros(shape, jnp.float32), sh.sharding))
        placements = self._acc_placements
        key = (static_key, layout, treedef,
               tuple((tuple(v.shape), str(v.dtype)) for v in dyn), placements)

        if key not in self._grad_cache:
            self._grad_cache[key] = self._build_grad_jit(
                static_key, layout, treedef, placements)

        from ..core.flags import flag_value
        update_key = (bool(flag_value("use_fused_adamw")),
                      bool(flag_value("adamw_stochastic_rounding")),
                      placements)
        if self._update_fn is None or getattr(self, "_update_key", None) \
                != update_key:
            self._update_key = update_key
            self._update_fn = self._build_update_jit(placements)

        param_vals = read_values(self.params)
        buf_vals = read_values(self.buffers)
        frozen_vals = read_values(self.frozen)
        rng_key = _random.next_key()
        loss_val, self._acc, new_bufs = self._grad_cache[key](
            param_vals, self._acc, buf_vals, frozen_vals, rng_key, dyn)
        for b, nv in zip(self.buffers, new_bufs):
            b._value = nv
        self._acc_count += 1
        if self._acc_count >= self.accumulate_steps:
            slot_vals = [opt._slots[id(p)] for p in self.params]
            opt._step_count += 1
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            step_i = jnp.asarray(opt._step_count, jnp.int32)
            new_pv, new_slots = self._update_fn(
                param_vals, slot_vals, self._acc, lr, step_i)
            for p, nv in zip(self.params, new_pv):
                p._value = nv
            for p, ns in zip(self.params, new_slots):
                opt._slots[id(p)] = ns
            self._acc = None
            self._acc_count = 0
        return Tensor(loss_val)


def save(layer, path, input_spec=None, **config):
    """paddle.jit.save analog: params + a serialized AOT-lowered program.

    The reference serializes a ProgramDesc+params (jit/api.py save). We save the
    state_dict plus an input spec; `jit.load` rebuilds a callable by re-jitting.
    For true AOT deployment see static.InputSpec + Predictor (inference module).
    """
    import pickle
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from ..framework_io import _pack
    state = {"state_dict": _pack(dict(layer.state_dict())),
             "class_name": type(layer).__name__,
             "input_spec": input_spec}
    with open(path + ".pdparams", "wb") as f:
        pickle.dump(state, f)


def load(path, **config):
    import pickle
    from ..framework_io import _unpack
    with open(path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    return _unpack(state["state_dict"])


def ignore_module(modules):
    return None


class ProgramTranslator:  # parity shim
    _instance = None

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag):
        pass


def enable_to_static(flag=True):
    pass


class TranslatedLayer:
    """Loaded-program layer (reference: jit/translated_layer.py
    TranslatedLayer — what jit.load returns in the reference). Our jit.load
    returns the callable program directly; this wrapper restores the layer
    interface (program(), train/eval flags) for API parity."""

    def __init__(self, fn, input_spec=None):
        self._fn = fn
        self._input_spec = input_spec
        self._is_test = True

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    forward = __call__

    def train(self):
        self._is_test = False
        return self

    def eval(self):
        self._is_test = True
        return self

    def program(self, method_name="forward"):
        return getattr(self._fn, "jaxpr", None)


_LOG_VERBOSITY = 0
_CODE_LEVEL = -1


def set_verbosity(level=0, also_to_stdout=False):
    """reference: jit/dy2static/logging_utils.py set_verbosity — transform
    logging verbosity."""
    global _LOG_VERBOSITY
    _LOG_VERBOSITY = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """reference: jit/dy2static/logging_utils.py set_code_level — which
    transformed-code stage to log."""
    global _CODE_LEVEL
    _CODE_LEVEL = int(level)
