"""paddle.jit analog — jax.jit is the capture+compile engine."""
from .api import (  # noqa: F401
    to_static, not_to_static, StaticFunction, TrainStep, save, load,
    enable_to_static, ignore_module, ProgramTranslator, TranslatedLayer,
    set_verbosity, set_code_level,
)
from .functional_call import collect_state, bind_state  # noqa: F401
