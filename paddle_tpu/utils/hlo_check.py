"""Compiled-program contract checks.

The reference proves its distributed schedules by construction — explicit NCCL
calls in the pipeline/sharding runtimes (e.g. group_sharded_stage2.py's
reduce_scatter loop). Under GSPMD the collectives are inserted by the
compiler, so the proof has to come from inspecting the *compiled* program:
which collectives were emitted, and how many bytes each device actually holds.

This module lowers a jitted function, compiles it, and exposes:

- collective op counts parsed from the optimized HLO text (async ``-start``
  forms counted once, ``-done`` halves ignored),
- per-device argument/output/temp byte totals from
  ``compiled.memory_analysis()`` (these are per-partition under SPMD),
- input/output shardings.

Used by tests/test_hlo_contracts.py to pin ZeRO-1/2/3 placements, pipeline
collective-permute counts, and per-device memory bounds on the virtual
8-device CPU mesh — the only possible multi-chip proof without a pod.
"""
from __future__ import annotations

import re
from dataclasses import dataclass

import jax

#: HLO collective op names (sync form; async appends ``-start``/``-done``)
COLLECTIVE_OPS = ("all-reduce", "reduce-scatter", "all-gather",
                  "collective-permute", "all-to-all", "collective-broadcast")


@dataclass
class CompileReport:
    hlo: str
    stats: object            # jaxlib CompiledMemoryStats (per device)
    input_shardings: tuple
    output_shardings: tuple

    def collective_counts(self) -> dict:
        counts = {}
        for op in COLLECTIVE_OPS:
            pat = re.compile(
                rf"=\s+(?:\([^)]*\)|\S+)\s+{re.escape(op)}(?:-start)?(?:\.\d+)?\(")
            counts[op] = len(pat.findall(self.hlo))
        return counts

    def count(self, op: str) -> int:
        return self.collective_counts()[op]

    # -- per-device byte totals (SPMD: sizes are per partition) --------------
    @property
    def arg_bytes(self) -> int:
        return int(self.stats.argument_size_in_bytes +
                   self.stats.alias_size_in_bytes)

    @property
    def out_bytes(self) -> int:
        return int(self.stats.output_size_in_bytes)

    @property
    def temp_bytes(self) -> int:
        return int(self.stats.temp_size_in_bytes)

    @property
    def peak_bytes(self) -> int:
        """Upper bound on per-device residency: args + outputs + temps."""
        return self.arg_bytes + self.out_bytes + self.temp_bytes


def compile_report(fn, *args, donate_argnums=(), static_argnums=()) -> CompileReport:
    """Jit + lower + compile ``fn`` on the current backend and report.

    ``fn`` may already be a jitted function (``jax.jit(f)``) — it is lowered
    as-is; otherwise it is wrapped with the given jit options.
    """
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn, donate_argnums=donate_argnums,
                     static_argnums=static_argnums)
    compiled = fn.lower(*args).compile()
    try:
        in_sh = tuple(compiled.input_shardings)
    except Exception:
        in_sh = ()
    try:
        out_sh = tuple(compiled.output_shardings)
    except Exception:
        out_sh = ()
    return CompileReport(compiled.as_text(), compiled.memory_analysis(),
                         in_sh, out_sh)


def tree_bytes(tree) -> int:
    """Total unsharded bytes of all array leaves in a pytree."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "dtype") and hasattr(leaf, "size"):
            total += leaf.size * leaf.dtype.itemsize
    return int(total)
