"""Per-op FLOPs accounting (reference: python/paddle/utils/flops.py — per-op
formulas used by profiler reports)."""
from __future__ import annotations

import numpy as np

_FLOP_FNS = {}


def register_flops(name):
    def deco(fn):
        _FLOP_FNS[name] = fn
        return fn
    return deco


def flops(op_type, input_shapes, attrs=None):
    """FLOPs for one op given {'X': [shape,...]}-style input shapes."""
    fn = _FLOP_FNS.get(op_type)
    if fn is None:
        return 0
    return int(fn(input_shapes, attrs or {}))


def _prod(s):
    return int(np.prod(s)) if len(s) else 1


@register_flops("matmul")
@register_flops("matmul_v2")
def _matmul_flops(shapes, attrs):
    x = list(shapes.get("X", shapes.get("x"))[0])
    y = list(shapes.get("Y", shapes.get("y"))[0])
    if attrs.get("transpose_X") or attrs.get("trans_x"):
        x[-1], x[-2] = x[-2], x[-1]
    if attrs.get("transpose_Y") or attrs.get("trans_y"):
        y[-1], y[-2] = y[-2], y[-1]
    batch = _prod(x[:-2])
    return 2 * batch * x[-2] * x[-1] * y[-1]


@register_flops("conv2d")
def _conv2d_flops(shapes, attrs):
    x = shapes.get("Input", shapes.get("x"))[0]      # NCHW
    w = shapes.get("Filter", shapes.get("weight"))[0]  # OIHW
    n, _, h, wd = x
    co, ci, kh, kw = w
    stride = attrs.get("strides", [1, 1])
    pad = attrs.get("paddings", [0, 0])
    oh = (h + 2 * pad[0] - kh) // stride[0] + 1
    ow = (wd + 2 * pad[1] - kw) // stride[1] + 1
    return 2 * n * co * oh * ow * ci * kh * kw


@register_flops("relu")
@register_flops("gelu")
@register_flops("silu")
@register_flops("softmax")
@register_flops("dropout")
def _elementwise_flops(shapes, attrs):
    key = next(iter(shapes))
    return _prod(shapes[key][0])


@register_flops("layer_norm")
@register_flops("rms_norm")
def _norm_flops(shapes, attrs):
    key = next(iter(shapes))
    return 5 * _prod(shapes[key][0])
