"""Unique name generator (reference: python/paddle/utils/unique_name.py —
generate/guard/switch over thread-local counter namespaces)."""
from __future__ import annotations

import contextlib
import threading

_local = threading.local()


def _gens():
    if not hasattr(_local, "stack"):
        _local.stack = [{}]
    return _local.stack


def generate(key):
    counters = _gens()[-1]
    n = counters.get(key, 0)
    counters[key] = n + 1
    return f"{key}_{n}"


def switch(new_generator=None):
    stack = _gens()
    old = stack[-1]
    stack[-1] = new_generator if new_generator is not None else {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    stack = _gens()
    stack.append({} if new_generator is None else dict())
    try:
        yield
    finally:
        stack.pop()
