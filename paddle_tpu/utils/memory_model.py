"""Memory-residency model for train steps: what the backward saves.

The fit proofs (tests/test_7b_scale.py) and the on-chip cross-validation
(bench.py BENCH_MODEL=memcheck) decompose per-device residency into

1. state — exact, from the compiled program's ``argument_size_in_bytes``;
2. backward residuals — trace-level, from jax's ``saved_residuals`` (the
   only backend-independent view that SEES remat; the CPU backend's
   ``temp_size_in_bytes`` is remat-blind, measured in round 3);
3. in-segment transients — the remainder against the TPU compiler's
   ``peak_memory_in_bytes`` (cross-validated on the real chip).

``saved_residuals`` is a PRIVATE jax API (jax._src.ad_checkpoint) — this
module is the single import site, with a loud failure naming the
dependency when a jax upgrade moves it.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def saved_residuals_compat(f, *args):
    """jax's saved_residuals, isolated behind one loud-failure import.

    Raises RuntimeError (not ImportError) with a clear message when the
    private API moves, so callers (tests skip; bench reports) can react
    instead of dying on an opaque AttributeError."""
    try:
        from jax._src.ad_checkpoint import saved_residuals
    except ImportError as e:  # pragma: no cover - jax upgrade path
        raise RuntimeError(
            "jax._src.ad_checkpoint.saved_residuals is gone in this jax "
            f"version ({jax.__version__}) — the residual-bytes memory model "
            "needs a replacement entry point (see "
            "paddle_tpu/utils/memory_model.py)") from e
    return saved_residuals(f, *args)


def residual_bytes(step, batch, dp_shards=1, seq_len=None):
    """Bytes the backward of a TrainStep saves between forward and backward
    (trace-level, backend-independent), EXCLUDING primal arguments (params —
    already counted in the compiled argument bytes).

    ``dp_shards``: degree of the data-parallel (ZeRO sharding) axis the
    batch is sharded over — batch-carrying residuals (leading dim B or B*S)
    are counted at 1/dp_shards per device; everything else fully replicated
    (conservative: layer boundaries are replicated under pure TP).

    ``seq_len`` non-None additionally ASSERTS no S x S residual survived
    (remat failure guard). Returns total bytes."""
    from ..jit.api import _make_loss_of, _split_leaves
    from ..jit.functional_call import read_values

    dyn, static_key, layout, treedef = _split_leaves(batch)
    # closed-over leaves must be concrete under this trace; batches are tiny
    dyn = [jnp.zeros(v.shape, v.dtype) if isinstance(v, jax.ShapeDtypeStruct)
           else v for v in dyn]
    loss_of_full = _make_loss_of(step.model, step.loss_fn, step.params,
                                 step.frozen, step.buffers, static_key,
                                 layout, treedef)
    frozen_vals = read_values(step.frozen)
    buf_vals = read_values(step.buffers)
    rng_key = jax.random.key(0)  # closed over: must be a real key array
    pv = read_values(step.params)
    batch_leading = set()
    for v in dyn:
        shape = getattr(v, "shape", ())
        if shape:
            batch_leading.add(shape[0])
            if len(shape) > 1:
                batch_leading.add(shape[0] * shape[1])

    def f(pv):
        loss, _bufs = loss_of_full(pv, frozen_vals, buf_vals, rng_key, dyn)
        return loss

    total = 0
    for aval, src in saved_residuals_compat(f, pv):
        if not getattr(aval, "shape", None):
            continue
        if "from the argument" in str(src):
            continue  # params: counted in compiled argument bytes
        shape = tuple(aval.shape)
        if seq_len is not None:
            assert not (seq_len in shape and shape.count(seq_len) >= 2), \
                f"S x S residual survived remat: {shape} ({src})"
        bytes_ = int(np.prod(shape)) * aval.dtype.itemsize
        if dp_shards > 1 and shape[0] in batch_leading:
            bytes_ //= dp_shards
        total += bytes_
    return total
