"""paddle.utils analog (reference: python/paddle/utils/ — unique_name,
deprecated, try_import, flops, dlpack)."""
from __future__ import annotations

import functools
import importlib
import threading
import warnings

from . import unique_name  # noqa: F401
from .flops import flops  # noqa: F401


def try_import(module_name, err_msg=None):
    """Reference: utils/lazy_import.py try_import."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"{module_name} is required but not installed")


def deprecated(update_to="", since="", reason="", level=0):
    """Reference: utils/deprecated.py — warn-once decorator."""
    def wrap(fn):
        warned = []

        @functools.wraps(fn)
        def inner(*args, **kwargs):
            msg = f"API {fn.__module__}.{fn.__name__} is deprecated"
            if since:
                msg += f" since {since}"
            if update_to:
                msg += f"; use {update_to} instead"
            if reason:
                msg += f" ({reason})"
            if level >= 2:           # hard-deprecated: always raise
                raise RuntimeError(msg)
            if not warned:           # soft: warn once
                warned.append(True)
                warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return inner

    return wrap


def run_check():
    """Sanity-check the install (reference: utils/install_check.py run_check)."""
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.ones([2, 2])
    y = (x @ x).sum()
    assert float(np.asarray(y._value)) == 8.0
    import jax
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! "
          f"{len(devs)} device(s): {[d.device_kind for d in devs]}")


class dlpack:
    """paddle.utils.dlpack parity namespace.

    Modern DLPack exchanges the protocol-carrying ARRAY (implements
    __dlpack__/__dlpack_device__), not a bare capsule — torch/numpy/jax
    from_dlpack all consume it directly."""

    @staticmethod
    def to_dlpack(x):
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        v = x._value if isinstance(x, Tensor) else x
        return jnp.asarray(v)

    @staticmethod
    def from_dlpack(ext_array):
        from ..core.tensor import Tensor
        import jax.numpy as jnp
        if isinstance(ext_array, Tensor):
            return ext_array
        if not hasattr(ext_array, "__dlpack__"):
            raise TypeError(
                "from_dlpack expects an object implementing the DLPack "
                "protocol (__dlpack__); legacy PyCapsules are not supported "
                "by this jax version")
        return Tensor(jnp.from_dlpack(ext_array))
from . import cpp_extension  # noqa: E402,F401


from . import download  # noqa: E402,F401
from .download import get_weights_path_from_url  # noqa: E402,F401


def require_version(min_version, max_version=None):
    """reference: utils/install_check.py require_version — assert the
    installed framework version is in [min_version, max_version]."""
    from .. import version as _version

    def parts(v):
        return [int(x.split("-")[0]) for x in str(v).split(".")[:3]
                if x.split("-")[0].isdigit()]

    cur = parts(_version.full_version)
    if min_version and parts(min_version) > cur:
        raise Exception(
            f"VersionError: paddle version {_version.full_version} is below "
            f"the required minimum {min_version}")
    if max_version and parts(max_version) < cur:
        raise Exception(
            f"VersionError: paddle version {_version.full_version} is above "
            f"the required maximum {max_version}")
