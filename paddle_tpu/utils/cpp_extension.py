"""paddle.utils.cpp_extension analog — JIT-compile custom C++ ops.

Reference: python/paddle/utils/cpp_extension/ (load/setup compile user C++
into an op library; PD_BUILD_OP registers kernels). TPU-native: the device
compute path is XLA — custom HOST ops compile with g++ into a shared library
bound via ctypes, and ``to_op`` lifts a C function into a framework op through
``jax.pure_callback`` (runs on host, composes with jit; supply ``vjp`` to make
it differentiable). This is the same native-extension story as the rest of the
runtime (csrc/): no pybind11, plain C ABI.

The C function contract: ``void f(const T* in0, const T* in1, ..., T* out,
int64_t n)`` with all buffers contiguous and n = element count of the output.
More elaborate signatures can be bound manually via ``load(...).lib``.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

__all__ = ["load", "get_build_directory", "CppExtension", "BuildExtension",
           "setup"]

_CACHE_DIR = os.environ.get(
    "PT_EXTENSIONS_DIR",
    os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions"))


def get_build_directory():
    os.makedirs(_CACHE_DIR, exist_ok=True)
    return _CACHE_DIR


class ExtensionModule:
    """Handle over a compiled user library."""

    def __init__(self, name, lib_path):
        self.name = name
        self.lib_path = lib_path
        self.lib = ctypes.CDLL(lib_path)

    def to_op(self, fn_name, num_inputs=1, dtype="float32", vjp=None,
              out_shape=None):
        """Lift ``void fn(const T* in..., T* out, int64_t n)`` into a
        framework op (host callback under jit; differentiable if vjp given).

        out_shape: fn(input_shapes...) -> output shape; defaults to the first
        input's shape."""
        import jax
        import jax.numpy as jnp
        from ..core.tensor import dispatch

        cfn = getattr(self.lib, fn_name)
        cfn.restype = None
        np_dt = np.dtype(dtype)

        def host_impl(*arrays):
            arrays = [np.ascontiguousarray(a, dtype=np_dt) for a in arrays]
            shape = (out_shape(*[a.shape for a in arrays])
                     if out_shape is not None else arrays[0].shape)
            out = np.empty(shape, dtype=np_dt)
            argv = [a.ctypes.data_as(ctypes.c_void_p) for a in arrays]
            cfn(*argv, out.ctypes.data_as(ctypes.c_void_p),
                ctypes.c_int64(out.size))
            return out

        def compute(*vals):
            shape = (out_shape(*[v.shape for v in vals])
                     if out_shape is not None else vals[0].shape)
            result = jax.pure_callback(
                host_impl, jax.ShapeDtypeStruct(shape, np_dt), *vals)
            return result

        if vjp is not None:
            compute_vjp = jax.custom_vjp(compute)

            def fwd(*vals):
                return compute(*vals), vals

            def bwd(res, g):
                grads = vjp(res, g)
                return tuple(jnp.asarray(gr) for gr in grads)

            compute_vjp.defvjp(fwd, bwd)
            inner = compute_vjp
        else:
            inner = compute

        def op(*tensors, name=None):
            return dispatch(lambda *v: inner(*v), tensors, {},
                            name=f"custom_{fn_name}")

        op.__name__ = fn_name
        return op


def load(name, sources, extra_cxx_cflags=None, extra_include_paths=None,
         extra_ldflags=None, build_directory=None, verbose=False):
    """Compile + load a custom op library (reference: cpp_extension.load).

    Returns an ExtensionModule; recompiles only when sources change."""
    build_dir = build_directory or get_build_directory()
    os.makedirs(build_dir, exist_ok=True)
    blobs = []
    for src in sources:
        with open(src, "rb") as f:
            blobs.append(f.read())
    digest = hashlib.sha256(b"\0".join(blobs)).hexdigest()[:16]
    lib_path = os.path.join(build_dir, f"lib{name}_{digest}.so")
    if not os.path.exists(lib_path):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17"]
        for inc in (extra_include_paths or []):
            cmd += ["-I", inc]
        cmd += (extra_cxx_cflags or [])
        cmd += list(sources) + (extra_ldflags or []) + ["-o", lib_path]
        if verbose:
            print(" ".join(cmd))
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"custom op build failed:\n{proc.stderr[-4000:]}")
    return ExtensionModule(name, lib_path)


# -- setup()-style API (reference: cpp_extension.setup) ----------------------

class CppExtension:
    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.kwargs = kwargs


class BuildExtension:
    """Placeholder command class for setup() parity."""

    @staticmethod
    def with_options(**kwargs):
        return BuildExtension


def setup(name, ext_modules, **kwargs):
    """Build-at-install parity shim: compiles immediately and returns the
    module handle (the reference integrates with setuptools; here the JIT
    `load` path is canonical)."""
    if isinstance(ext_modules, (list, tuple)):
        ext = ext_modules[0]
    else:
        ext = ext_modules
    return load(name, ext.sources, **ext.kwargs)


class CUDAExtension(CppExtension):
    """reference: utils/cpp_extension/cpp_extension.py CUDAExtension — the
    accelerator-extension descriptor. The TPU build has no nvcc; sources build
    with the host toolchain and reach the device through pure_callback (see
    `load` above), matching how CppExtension behaves here."""

    def __init__(self, sources, *args, **kwargs):
        super().__init__(sources, *args, **kwargs)
        self.cuda = True
