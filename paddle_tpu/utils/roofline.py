"""Per-fusion roofline analysis for compiled TPU programs.

The reference ships a tuned conv library with layout+algorithm autotuning
(paddle/phi/kernels/gpudnn/conv_kernel.cu, phi/kernels/autotune/
auto_tune_base.h); the TPU-native counterpart question is whether XLA's
conv fusions run at THIS chip's roofline. This module answers it with
measurement, not assertion:

  1. parse the optimized HLO of a compiled step — per entry-level
     instruction: FLOPs (dots/convs, recursively through fused
     computations) and HBM bytes (operand + result sizes);
  2. run the step under ``jax.profiler.trace`` and read the DEVICE-track
     durations per instruction (host-side timing has a ~1 ms dispatch
     floor through the axon tunnel; device track is exact);
  3. join the two: each fusion's achieved FLOP/s and B/s against its own
     roofline bound  t_bound = max(flops/peak, bytes/bw_measured).

Used by ``BENCH_MODEL=conv_roofline`` (bench.py) to regenerate
``docs/artifacts/conv_roofline_proof.json`` and by
tests/test_roofline_tool.py for the parser contract.
"""
from __future__ import annotations

import glob
import gzip
import json
import os
import re
import tempfile

__all__ = [
    "parse_hlo_costs", "profile_device_events", "roofline_table",
]

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1,
    "f8e5m2": 1, "s16": 2, "u16": 2, "bf16": 2, "f16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "opaque": 0,
}

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(type_str: str) -> int:
    """Bytes of an HLO type string; tuples sum their elements."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(type_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _shape_dims(type_str: str):
    m = _SHAPE_RE.search(type_str)
    return [int(d) for d in m.group(2).split(",") if d] if m else []


_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\((.*?)\)\s*->\s*(.+?)\s*\{\s*$")
_PARAM_RE = re.compile(r"%?([\w.\-]+)\s*:\s*((?:\([^)]*\)|[a-z0-9]+\[[0-9,]*\]))")
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_OPERAND_RE = re.compile(r"%([\w.\-]+)")
_DTYPE_SHAPE_RE = re.compile(r"[a-z0-9]+\[[0-9,]*\]")
_OP_OPEN_RE = re.compile(r"([\w\-]+)\(")


def _match_depth(s: str, i: int) -> int:
    """Index just past the bracket group opening at s[i] ('(' or '{'),
    counting nested brackets of both kinds (HLO layouts nest parens
    inside braces: bf16[8,...]{3,2,1,0:T(8,128)(2,1)S(1)})."""
    depth = 0
    opens, closes = "({", ")}"
    while i < len(s):
        if s[i] in opens:
            depth += 1
        elif s[i] in closes:
            depth -= 1
            if depth == 0:
                return i + 1
        i += 1
    return len(s)


def _split_instr(line: str):
    """'%name = TYPE op(operands), attrs' -> (name, type, op, rest).
    TYPE may be a tuple of layouted shapes — regexes can't match its
    nested brackets, which is exactly how multi-output fusions (conv+BN
    stats) went uncosted in the first cut of this parser."""
    s = line.strip()
    if s.startswith("ROOT "):
        s = s[5:]
    if not s.startswith("%"):
        return None
    eq = s.find(" = ")
    if eq < 0:
        return None
    name = s[1:eq]
    rest = s[eq + 3:]
    if not rest:
        return None
    if rest[0] == "(":
        i = _match_depth(rest, 0)
    else:
        m = _DTYPE_SHAPE_RE.match(rest)
        if not m:
            return None
        i = m.end()
    while i < len(rest) and rest[i] == "{":
        i = _match_depth(rest, i)
    type_str = rest[:i]
    tail = rest[i:].lstrip()
    m = _OP_OPEN_RE.match(tail)
    if not m:
        return None
    return name, type_str, m.group(1), tail[m.end():]


def _parse_computations(hlo_text: str):
    """-> {comp_name: {"params": {name: type}, "result": type,
    "instrs": [(name, type, op, rest)], "is_entry": bool}}"""
    comps = {}
    cur = None
    for line in hlo_text.splitlines():
        if cur is None:
            m = _COMP_RE.match(line)
            if m and ("->" in line):
                name, params, result = m.group(1), m.group(2), m.group(3)
                cur = {"params": dict(
                            (n, t) for n, t in _PARAM_RE.findall(params)),
                       "result": result, "instrs": [],
                       "is_entry": line.startswith("ENTRY")}
                comps[name] = cur
            continue
        if line.startswith("}"):
            cur = None
            continue
        parsed = _split_instr(line)
        if parsed:
            cur["instrs"].append(parsed)
    return comps


def _win_attr(window: str, key: str, nd: int, default: int):
    m = re.search(rf"{key}=([0-9x_\-]+)", window)
    fallback = [(default, default)] * nd if key == "pad" else [default] * nd
    if not m:
        return fallback
    parts = m.group(1).split("x")
    if len(parts) != nd:
        return fallback
    if key == "pad":
        return [tuple(int(v) for v in p.split("_")) for p in parts]
    return [int(p) for p in parts]


def _conv_flops(type_str, rest, symtab):
    """Useful MACs of an HLO convolution: 2 x (non-spatial out dims) x
    rhs reduction features x per-dim VALID (output, window) pairs.

    Naive 2*prod(out)*prod(window)*C counts padding and dilation zeros as
    real math — a full-correlation filter-grad (window 56x56, pad 55)
    would read as 10 TFLOP of a 13 GFLOP op. Valid-pair counting per
    spatial dim makes the count match the model-level FLOP accounting the
    MFU numbers use."""
    out = _shape_dims(type_str)
    m = re.search(r"dim_labels=([\w]+)_([\w]+)->([\w]+)", rest)
    ops = _OPERAND_RE.findall(rest.split(", window=")[0])
    if not m or len(ops) < 2 or ops[0] not in symtab \
            or ops[1] not in symtab:
        return 0
    lhs_l, rhs_l, out_l = m.group(1), m.group(2), m.group(3)
    lhs = _shape_dims(symtab[ops[0]])
    rhs = _shape_dims(symtab[ops[1]])
    if len(rhs) != len(rhs_l) or len(lhs) != len(lhs_l) \
            or len(out) != len(out_l):
        return 0
    k_feat = rhs[rhs_l.index("i")]
    spatial = [ch for ch in out_l if ch.isdigit()]
    win = re.search(r"window=\{([^}]*)\}", rest)
    window = win.group(1) if win else ""
    nd = len(spatial)
    sizes = _win_attr(window, "size", nd, 1)
    strides = _win_attr(window, "stride", nd, 1)
    pads = _win_attr(window, "pad", nd, 0)
    ldil = _win_attr(window, "lhs_dilate", nd, 1)
    rdil = _win_attr(window, "rhs_dilate", nd, 1)
    # non-spatial output element count (batch x features)
    n = 1
    for i, ch in enumerate(out_l):
        if not ch.isdigit():
            n *= out[i]
    pairs = 1
    for d, ch in enumerate(spatial):
        O = out[out_l.index(ch)]
        K = sizes[d]
        L = lhs[lhs_l.index(ch)]
        span = (L - 1) * ldil[d] + 1  # dilated base extent
        valid = 0
        for o in range(O):
            base = o * strides[d] - pads[d][0]
            for kk in range(K):
                pos = base + kk * rdil[d]
                if 0 <= pos < span and pos % ldil[d] == 0:
                    valid += 1
        pairs *= valid
    return 2 * n * k_feat * pairs


def _dot_flops(type_str, rest, symtab):
    out = _shape_dims(type_str)
    n = 1
    for d in out:
        n *= d
    m = re.search(r"lhs_contracting_dims=\{([0-9,]*)\}", rest)
    ops = _OPERAND_RE.findall(rest.split(", lhs_")[0])
    if not m or not ops or ops[0] not in symtab:
        return 0
    lhs = _shape_dims(symtab[ops[0]])
    k = 1
    for i in (int(x) for x in m.group(1).split(",") if x):
        if i < len(lhs):
            k *= lhs[i]
    return 2 * n * k


def _comp_flops(comp_name, comps, memo):
    """Total dot/conv FLOPs of a computation, following nested fusion/call
    edges. Returns (flops, kinds) where kinds is a set like {"conv","dot"}."""
    if comp_name in memo:
        return memo[comp_name]
    comp = comps.get(comp_name)
    if comp is None:
        return 0, set()
    memo[comp_name] = (0, set())  # cycle guard
    symtab = dict(comp["params"])
    flops, kinds = 0, set()
    for name, type_str, op, rest in comp["instrs"]:
        symtab[name] = type_str
        if op == "convolution":
            f = _conv_flops(type_str, rest, symtab)
            flops += f
            if f:
                kinds.add("conv")
        elif op == "dot":
            f = _dot_flops(type_str, rest, symtab)
            flops += f
            if f:
                kinds.add("dot")
        elif op == "custom-call":
            kinds.add("custom")
        elif op in ("fusion", "call", "while", "conditional"):
            for callee in _CALLS_RE.findall(rest) or _operand_comps(op, rest):
                sub_f, sub_k = _comp_flops(callee, comps, memo)
                flops += sub_f
                kinds |= sub_k
    memo[comp_name] = (flops, kinds)
    return flops, kinds


def _operand_comps(op, rest):
    """while/conditional reference computations via body=/condition= etc."""
    if op == "while":
        return re.findall(r"(?:body|condition)=%?([\w.\-]+)", rest)
    if op == "conditional":
        return re.findall(r"\w+_computation=%?([\w.\-]+)", rest)
    return []


def parse_hlo_costs(hlo_text: str):
    """Per entry-level instruction: {"flops", "bytes", "kind", "op_name"}.

    bytes = operand bytes + result bytes (the fusion's HBM traffic bound,
    assuming perfect reuse inside the fusion); flops follow nested fusions.
    """
    comps = _parse_computations(hlo_text)
    entry = next((c for c in comps.values() if c["is_entry"]), None)
    if entry is None:
        return {}
    memo = {}
    symtab = dict(entry["params"])
    out = {}
    for name, type_str, op, rest in entry["instrs"]:
        symtab[name] = type_str
        res_bytes = _shape_bytes(type_str)
        opnames = _OPERAND_RE.findall(rest.split("metadata=")[0])
        # operands whose producing instruction lives in memory space S(1)
        # (VMEM, placed there by memory-space-assignment prefetch copies)
        # are NOT HBM traffic of this fusion — the copy-start/copy-done
        # that staged them is billed separately on the device track
        op_bytes, vmem_bytes = 0, 0
        for o in opnames:
            if o not in symtab:
                continue
            b = _shape_bytes(symtab[o])
            if "S(1)" in symtab[o]:
                vmem_bytes += b
            else:
                op_bytes += b
        if "S(1)" in type_str:
            vmem_bytes += res_bytes
            res_bytes = 0
        flops, kinds = 0, set()
        if op == "convolution":
            flops = _conv_flops(type_str, rest, symtab)
            kinds = {"conv"} if flops else set()
        elif op == "dot":
            flops = _dot_flops(type_str, rest, symtab)
            kinds = {"dot"} if flops else set()
        elif op in ("fusion", "call", "while", "conditional"):
            for callee in _CALLS_RE.findall(rest) or _operand_comps(op, rest):
                f, k = _comp_flops(callee, comps, memo)
                flops += f
                kinds |= k
        mname = re.search(r'op_name="([^"]*)"', rest)
        op_name = mname.group(1) if mname else ""
        if op == "custom-call" or "custom" in kinds \
                or "pallas_call" in op_name:
            # a Pallas kernel's FLOPs are invisible to HLO parsing — its
            # roofline must be argued from its OWN cost model, not this
            # table (kind="custom" keeps it out of the conv aggregates)
            kind = "custom"
        elif "conv" in kinds:
            kind = "conv"
        elif "dot" in kinds:
            kind = "dot"
        else:
            kind = "other"
        out[name] = {
            "flops": flops,
            "bytes": op_bytes + res_bytes,
            "vmem_bytes": vmem_bytes,
            "kind": kind,
            "op": op,
            "op_name": op_name,
        }
    return out


def profile_device_events(run_fn, steps: int = 4, trace_dir: str = None):
    """Run ``run_fn(steps)`` under jax.profiler.trace; return
    ({instr_name: {"count", "total_us"}}, device_total_us) from the
    device track. ``run_fn`` must sync before returning (scalar fetch —
    block_until_ready is a no-op through the axon tunnel)."""
    import jax

    td = trace_dir or tempfile.mkdtemp(prefix="pt_roofline_")
    with jax.profiler.trace(td):
        run_fn(steps)
    paths = sorted(glob.glob(
        os.path.join(td, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        raise RuntimeError(f"no trace produced under {td}")
    events = json.loads(gzip.open(paths[-1]).read())["traceEvents"]
    # the dedupe-aware parse (module spans / per-op spans / bare-number
    # "Steps" markers each routed exactly once) lives in the profiler —
    # one regression-tested copy shared by every trace consumer
    from ..profiler import summarize_device_trace
    return summarize_device_trace(events)


def roofline_table(hlo_text: str, events, steps: int,
                   peak_flops: float, hbm_bw: float):
    """Join HLO costs with device durations -> per-instruction rows.

    Each row: achieved TFLOP/s and GB/s, its own roofline bound
    t_bound = max(flops/peak, bytes/bw), and efficiency = t_bound/t_meas
    (1.0 = running AT the roofline; small = leaving the machine idle).
    """
    costs = parse_hlo_costs(hlo_text)
    rows = []
    unmatched_us = 0.0
    for name, ev in events.items():
        us = ev["total_us"] / max(steps, 1)
        cost = costs.get(name)
        if cost is None or us <= 0:
            unmatched_us += us
            continue
        t = us / 1e6
        t_bound = max(cost["flops"] / peak_flops,
                      cost["bytes"] / hbm_bw) if (
                          cost["flops"] or cost["bytes"]) else 0.0
        rows.append({
            "name": name,
            "kind": cost["kind"],
            "op_name": cost["op_name"][:120],
            "time_us": round(us, 1),
            "flops": cost["flops"],
            "bytes": cost["bytes"],
            "achieved_tflops": round(cost["flops"] / t / 1e12, 2),
            "achieved_gbs": round(cost["bytes"] / t / 1e9, 1),
            "bound_us": round(t_bound * 1e6, 1),
            "bound_by": ("compute" if cost["flops"] / peak_flops
                         >= cost["bytes"] / hbm_bw else "memory"),
            "roofline_eff": round(t_bound / t, 3) if t_bound else None,
        })
    rows.sort(key=lambda r: -r["time_us"])
    return rows, unmatched_us  # already per-step (us was divided above)
