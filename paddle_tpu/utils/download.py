"""paddle.utils.download — weight fetching (reference: utils/download.py).

Zero-egress environment: URLs resolve only through the local cache dir
(~/.cache/paddle/hapi/weights or PADDLE_HOME); a cache miss raises with
instructions instead of downloading.
"""
from __future__ import annotations

import os

__all__ = ["get_weights_path_from_url"]


def _cache_dir():
    root = os.environ.get("PADDLE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "paddle"))
    return os.path.join(root, "hapi", "weights")


def get_path_from_url(url, root_dir=None, md5sum=None, check_exist=True):
    fname = os.path.basename(url.split("?")[0])
    path = os.path.join(root_dir or _cache_dir(), fname)
    if os.path.exists(path):
        return path
    raise RuntimeError(
        f"weights for {url!r} not found at {path} and this environment has "
        "no network access — place the file there manually")


def get_weights_path_from_url(url, md5sum=None):
    """reference: download.py get_weights_path_from_url."""
    return get_path_from_url(url, _cache_dir(), md5sum)
