"""paddle.fft analog — discrete Fourier transform family.

Reference: python/paddle/fft.py (fft/ifft/rfft/... wrapping phi fft kernels, which on
GPU ride cuFFT and on CPU ride pocketfft — SURVEY.md §2.10). TPU-native: every
transform lowers to ``jnp.fft`` (XLA FFT HLO), dispatched through the eager tape so
gradients and jit both work from the same definitions.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import dispatch
from ..ops.creation import to_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = (None, "backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return norm or "backward"


def _unary(jfn, op_name):
    def op(x, n=None, axis=-1, norm="backward", name=None):
        norm_ = _check_norm(norm)

        def fn(v):
            return jfn(v, n=n, axis=axis, norm=norm_)

        return dispatch(fn, (x,), {}, name=op_name)

    op.__name__ = op_name
    return op


def _axes_op(jfn, op_name, default_axes=None):
    def op(x, s=None, axes=default_axes, norm="backward", name=None):
        norm_ = _check_norm(norm)

        def fn(v):
            return jfn(v, s=s, axes=axes, norm=norm_)

        return dispatch(fn, (x,), {}, name=op_name)

    op.__name__ = op_name
    return op


fft = _unary(jnp.fft.fft, "fft")
ifft = _unary(jnp.fft.ifft, "ifft")
rfft = _unary(jnp.fft.rfft, "rfft")
irfft = _unary(jnp.fft.irfft, "irfft")
hfft = _unary(jnp.fft.hfft, "hfft")
ihfft = _unary(jnp.fft.ihfft, "ihfft")

fft2 = _axes_op(jnp.fft.fft2, "fft2", default_axes=(-2, -1))
ifft2 = _axes_op(jnp.fft.ifft2, "ifft2", default_axes=(-2, -1))
rfft2 = _axes_op(jnp.fft.rfft2, "rfft2", default_axes=(-2, -1))
irfft2 = _axes_op(jnp.fft.irfft2, "irfft2", default_axes=(-2, -1))


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    # scipy/paddle semantics: forward FFT over the leading axes, then a
    # Hermitian-to-real transform along the last axis
    norm_ = _check_norm(norm)

    def fn(v):
        n = None if s is None else s[-1]
        inner = jnp.fft.fftn(v, s=None if s is None else s[:-1], axes=axes[:-1],
                             norm=norm_)
        return jnp.fft.hfft(inner, n=n, axis=axes[-1], norm=norm_)

    return dispatch(fn, (x,), {}, name="hfft2")


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    norm_ = _check_norm(norm)

    def fn(v):
        n = None if s is None else s[-1]
        inner = jnp.fft.ihfft(v, n=n, axis=axes[-1], norm=norm_)
        return jnp.fft.ifftn(inner, s=None if s is None else s[:-1], axes=axes[:-1],
                             norm=norm_)

    return dispatch(fn, (x,), {}, name="ihfft2")


fftn = _axes_op(jnp.fft.fftn, "fftn")
ifftn = _axes_op(jnp.fft.ifftn, "ifftn")
rfftn = _axes_op(jnp.fft.rfftn, "rfftn")
irfftn = _axes_op(jnp.fft.irfftn, "irfftn")


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    norm_ = _check_norm(norm)

    def fn(v):
        ax = axes if axes is not None else tuple(range(v.ndim))
        n = None if s is None else s[-1]
        inner = jnp.fft.fftn(v, s=None if s is None else s[:-1], axes=ax[:-1],
                             norm=norm_)
        return jnp.fft.hfft(inner, n=n, axis=ax[-1], norm=norm_)

    return dispatch(fn, (x,), {}, name="hfftn")


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    norm_ = _check_norm(norm)

    def fn(v):
        ax = axes if axes is not None else tuple(range(v.ndim))
        n = None if s is None else s[-1]
        inner = jnp.fft.ihfft(v, n=n, axis=ax[-1], norm=norm_)
        return jnp.fft.ifftn(inner, s=None if s is None else s[:-1], axes=ax[:-1],
                             norm=norm_)

    return dispatch(fn, (x,), {}, name="ihfftn")


def fftfreq(n, d=1.0, dtype=None, name=None):
    return to_tensor(jnp.fft.fftfreq(n, d=d), dtype=dtype)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return to_tensor(jnp.fft.rfftfreq(n, d=d), dtype=dtype)


def fftshift(x, axes=None, name=None):
    def fn(v):
        return jnp.fft.fftshift(v, axes=axes)

    return dispatch(fn, (x,), {}, name="fftshift")


def ifftshift(x, axes=None, name=None):
    def fn(v):
        return jnp.fft.ifftshift(v, axes=axes)

    return dispatch(fn, (x,), {}, name="ifftshift")
