"""paddle.nn.quant analog — quantized layers + weight-only helpers.

Reference: python/paddle/nn/quant/ (qat layer wrappers, and the weight-only
GEMM helpers weight_quantize/weight_only_linear used for LLM inference).
TPU-native: weight-only int8 keeps weights in HBM at half the bytes and
dequantizes inline — XLA fuses the scale-multiply into the matmul, which is the
memory-bandwidth win the reference gets from its cutlass weight-only kernels.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ..layer_base import Layer
from ...quantization import (  # noqa: F401
    QuantedLinear, QuantedConv2D, QuantizedLinearInfer,
    FakeQuanterWithAbsMaxObserver, FakeQuanterChannelWiseAbsMaxObserver,
    quantize_linear, dequantize_linear, fake_quantize,
)

__all__ = [
    "QuantedLinear", "QuantedConv2D", "QuantizedLinearInfer",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMaxObserver",
    "quantize_linear", "dequantize_linear", "fake_quantize",
    "weight_quantize", "weight_dequantize", "weight_only_linear", "llm_int8_linear",
]


def weight_quantize(weight, algo="weight_only_int8", group_size=-1):
    """Per-out-channel int8 weight quantization.

    Returns (quantized int8 Tensor [in, out], scales float Tensor [out]).
    Reference: nn/quant/quantized_linear.py weight_quantize."""
    if algo not in ("weight_only_int8", "llm.int8"):
        raise NotImplementedError(f"algo {algo!r} (int4 needs packed storage)")
    w = weight.numpy() if isinstance(weight, Tensor) else np.asarray(weight)
    scales = np.maximum(np.abs(w).max(axis=0), 1e-9).astype(np.float32) / 127.0
    q = np.clip(np.round(w / scales[None, :]), -127, 127).astype(np.int8)
    return Tensor(q), Tensor(scales)


def weight_dequantize(quant_weight, scale, algo="weight_only_int8"):
    def fn(q, s):
        return q.astype(s.dtype) * s[None, :]

    return dispatch(fn, (quant_weight, scale), {}, name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", group_size=-1):
    """y = x @ dequant(w_int8) + b; the dequant fuses into the matmul operand.
    Reference: nn/quant/quantized_linear.py weight_only_linear."""
    def fn(xv, q, s, b):
        w = q.astype(xv.dtype) * s.astype(xv.dtype)[None, :]
        y = jnp.matmul(xv, w)
        if b is not None:
            y = y + b
        return y

    return dispatch(fn, (x, weight, weight_scale, bias), {},
                    name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8 decomposition (reference: nn/quant/quantized_linear.py
    llm_int8_linear): inlier activation columns are themselves quantized to
    int8 (per-row dynamic scale) and multiplied against the int8 weights —
    the int8×int8 path — while outlier columns (|x| > threshold) run in full
    precision against the dequantized weights."""
    def fn(xv, q, s, b):
        w = q.astype(xv.dtype) * s.astype(xv.dtype)[None, :]
        absx = jnp.max(jnp.abs(xv), axis=tuple(range(xv.ndim - 1)))
        outlier = absx > threshold
        x_main = jnp.where(outlier, 0.0, xv)
        x_out = jnp.where(outlier, xv, 0.0)
        # dynamic per-row int8 quantization of the inlier activations
        row_scale = jnp.maximum(
            jnp.max(jnp.abs(x_main), axis=-1, keepdims=True), 1e-9) / 127.0
        xq = jnp.clip(jnp.round(x_main / row_scale), -127, 127)
        # int8 x int8 accumulated in int32, then rescaled (XLA lowers this to
        # the TPU int matmul path); outliers take the fp route
        y_main = jnp.matmul(xq.astype(jnp.int32),
                            q.astype(jnp.int32)).astype(xv.dtype)
        y_main = y_main * row_scale * s.astype(xv.dtype)[None, :]
        y = y_main + jnp.matmul(x_out, w)
        if b is not None:
            y = y + b
        return y

    return dispatch(fn, (x, weight, weight_scale, bias), {},
                    name="llm_int8_linear")


class Stub(Layer):
    """Quantization insertion point (reference: nn/quant/stub.py Stub): a
    no-op layer the QAT pass replaces with the configured quanter."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, input):
        return input


__all__.append("Stub")
