"""paddle.nn.quant analog — quantized layers + weight-only helpers.

Reference: python/paddle/nn/quant/ (qat layer wrappers, and the weight-only
GEMM helpers weight_quantize/weight_only_linear used for LLM inference).
TPU-native: weight-only int8 keeps weights in HBM at half the bytes and
dequantizes inline — XLA fuses the scale-multiply into the matmul, which is the
memory-bandwidth win the reference gets from its cutlass weight-only kernels.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.tensor import Tensor, dispatch
from ..layer_base import Layer
from ...quantization import (  # noqa: F401
    QuantedLinear, QuantedConv2D, QuantizedLinearInfer,
    FakeQuanterWithAbsMaxObserver, FakeQuanterChannelWiseAbsMaxObserver,
    quantize_linear, dequantize_linear, fake_quantize,
)

__all__ = [
    "QuantedLinear", "QuantedConv2D", "QuantizedLinearInfer",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMaxObserver",
    "quantize_linear", "dequantize_linear", "fake_quantize",
    "weight_quantize", "weight_dequantize", "weight_only_linear", "llm_int8_linear",
    "WeightOnlyLinear", "quantize_linears_for_inference",
]


def weight_quantize(weight, algo="weight_only_int8", group_size=-1):
    """Per-out-channel weight quantization.

    int8: returns (int8 Tensor [in, out], scales float Tensor [out]).
    int4: two values pack into each int8 byte along the input dim — returns
    (int8 Tensor [ceil(in/2), out] with row 2k in the low nibble and row 2k+1
    in the high nibble, scales [out]); odd input dims are zero-padded.
    Reference: nn/quant/quantized_linear.py weight_quantize."""
    if algo not in ("weight_only_int8", "llm.int8", "weight_only_int4"):
        raise NotImplementedError(f"unknown weight_quantize algo {algo!r}")
    w = weight.numpy() if isinstance(weight, Tensor) else np.asarray(weight)
    if algo == "weight_only_int4":
        scales = np.maximum(np.abs(w).max(axis=0), 1e-9).astype(np.float32) / 7.0
        q = np.clip(np.round(w / scales[None, :]), -8, 7).astype(np.int8)
        if q.shape[0] % 2:
            q = np.concatenate([q, np.zeros((1, q.shape[1]), np.int8)])
        packed = ((q[0::2] & 0x0F) | ((q[1::2] & 0x0F) << 4)).astype(np.int8)
        return Tensor(packed), Tensor(scales)
    scales = np.maximum(np.abs(w).max(axis=0), 1e-9).astype(np.float32) / 127.0
    q = np.clip(np.round(w / scales[None, :]), -127, 127).astype(np.int8)
    return Tensor(q), Tensor(scales)


def _nibbles(p):
    """Sign-extended (low, high) int4 nibbles of a packed int8 tensor —
    THE unpacking convention (row 2k low, row 2k+1 high); shared by
    weight_dequantize and weight_only_linear."""
    low = jnp.right_shift(jnp.left_shift(p, 4), 4)
    high = jnp.right_shift(p, 4)
    return low, high


def _unpack_int4(p, n_in=None):
    """[rows, out] packed int8 -> [2*rows, out] int4 values, truncated to
    n_in rows."""
    low, high = _nibbles(p)
    q = jnp.stack([low, high], axis=1).reshape(-1, p.shape[-1])
    return q if n_in is None else q[:n_in]


def weight_dequantize(quant_weight, scale, algo="weight_only_int8",
                      in_features=None):
    """Inverse of weight_quantize. For int4, pass ``in_features`` to strip
    the zero-pad row of odd input dims (otherwise the padded [2*rows, out]
    shape is returned)."""
    def fn(q, s):
        if algo == "weight_only_int4":
            q = _unpack_int4(q, in_features)
        return q.astype(s.dtype) * s[None, :]

    return dispatch(fn, (quant_weight, scale), {}, name="weight_dequantize")


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", group_size=-1):
    """y = x @ dequant(w) + b; the dequant fuses into the matmul operand.
    weight_dtype='int4' consumes the packed layout from weight_quantize —
    computed as TWO half-size matmuls on the low/high nibbles (even/odd
    input rows), which avoids materializing the interleave-unpacked
    [in, out] matrix the stack+reshape form costs per call.
    Reference: nn/quant/quantized_linear.py weight_only_linear."""
    def fn(xv, q, s, b):
        sb = s.astype(xv.dtype)
        if weight_dtype == "int4":
            n_in = xv.shape[-1]
            from ...core.flags import flag_value
            from ...ops.kernels.int4_matmul import (int4_matmul,
                                                    int4_matmul_tileable)
            rows = int(np.prod(xv.shape[:-1]))
            # decode-shaped GEMMs only: the kernel keeps whole x row-blocks
            # in VMEM, so many-row (prefill/training) calls would blow the
            # scoped-vmem budget — those are compute-bound anyway and keep
            # the split-nibble path
            use_pallas = (flag_value("use_pallas_int4")
                          and jax.default_backend() == "tpu"
                          and rows <= 128
                          and int4_matmul_tileable(n_in, q.shape[-1]))
            if use_pallas:
                # fused dequant-matmul: packed bytes stream straight to the
                # MXU with in-register nibble extraction (halves int8's
                # weight traffic; ~1.4x its decode GEMM on v5e). The kernel
                # has no VJP of its own, so a custom_vjp supplies the
                # x-gradient via the split-nibble dequant matmul (small-
                # batch fine-tune/eval graphs differentiate through this).
                @jax.custom_vjp
                def _mm(x2d):
                    return int4_matmul(x2d, q, s)

                def _mm_fwd(x2d):
                    return _mm(x2d), None

                def _mm_bwd(_, dy):
                    low, high = _nibbles(q)
                    sd = s.astype(dy.dtype)
                    dxe = jnp.matmul(dy, (low.astype(dy.dtype)
                                          * sd[None, :]).T)
                    dxo = jnp.matmul(dy, (high.astype(dy.dtype)
                                          * sd[None, :]).T)
                    # W rows interleave low/high nibbles: dx[2i]=dxe[i],
                    # dx[2i+1]=dxo[i], truncated to odd in_features
                    dx = jnp.stack([dxe, dxo], axis=-1).reshape(
                        dy.shape[:-1] + (2 * low.shape[0],))[..., :n_in]
                    return (dx,)

                _mm.defvjp(_mm_fwd, _mm_bwd)
                lead = xv.shape[:-1]
                y = _mm(xv.reshape(-1, n_in))
                y = y.reshape(lead + (q.shape[-1],))
            else:
                low, high = _nibbles(q)
                x_even = xv[..., 0::2]
                x_odd = xv[..., 1::2]
                if n_in % 2:  # odd in_features: pad row pairs with nothing
                    x_odd = jnp.pad(x_odd,
                                    [(0, 0)] * (xv.ndim - 1) + [(0, 1)])
                y = (jnp.matmul(x_even, low.astype(xv.dtype) * sb[None, :])
                     + jnp.matmul(x_odd, high.astype(xv.dtype) * sb[None, :]))
        else:
            w = q.astype(xv.dtype) * sb[None, :]
            y = jnp.matmul(xv, w)
        if b is not None:
            y = y + b
        return y

    return dispatch(fn, (x, weight, weight_scale, bias), {},
                    name="weight_only_linear")


def llm_int8_linear(x, weight, bias=None, weight_scale=None, threshold=6.0):
    """LLM.int8 decomposition (reference: nn/quant/quantized_linear.py
    llm_int8_linear): inlier activation columns are themselves quantized to
    int8 (per-row dynamic scale) and multiplied against the int8 weights —
    the int8×int8 path — while outlier columns (|x| > threshold) run in full
    precision against the dequantized weights."""
    def fn(xv, q, s, b):
        w = q.astype(xv.dtype) * s.astype(xv.dtype)[None, :]
        absx = jnp.max(jnp.abs(xv), axis=tuple(range(xv.ndim - 1)))
        outlier = absx > threshold
        x_main = jnp.where(outlier, 0.0, xv)
        x_out = jnp.where(outlier, xv, 0.0)
        # dynamic per-row int8 quantization of the inlier activations
        row_scale = jnp.maximum(
            jnp.max(jnp.abs(x_main), axis=-1, keepdims=True), 1e-9) / 127.0
        xq = jnp.clip(jnp.round(x_main / row_scale), -127, 127)
        # int8 x int8 accumulated in int32, then rescaled (XLA lowers this to
        # the TPU int matmul path); outliers take the fp route
        y_main = jnp.matmul(xq.astype(jnp.int32),
                            q.astype(jnp.int32)).astype(xv.dtype)
        y_main = y_main * row_scale * s.astype(xv.dtype)[None, :]
        y = y_main + jnp.matmul(x_out, w)
        if b is not None:
            y = y + b
        return y

    return dispatch(fn, (x, weight, weight_scale, bias), {},
                    name="llm_int8_linear")


class WeightOnlyLinear(Layer):
    """Deploy-form Linear with weight-only quantized STORAGE: the fp weight
    is dropped; forward streams the int8/int4 weight and fuses dequant into
    the matmul operand load. On a weight-bandwidth-bound decode step this
    halves (int8) or quarters (int4) the HBM bytes per token — the TPU
    analog of the reference's cutlass weight-only GEMM serving path
    (nn/quant/quantized_linear.py weight_only_linear + paddlenlp
    WeightOnlyLinear)."""

    def __init__(self, linear, weight_dtype="int8"):
        super().__init__()
        from ..layer_base import Parameter
        q, s = weight_quantize(linear.weight,
                               algo=f"weight_only_{weight_dtype}")
        # device-resident storage: weight_quantize computes host-side
        # (numpy); a numpy-backed param would be re-uploaded on EVERY jitted
        # call (measured ~15 s/call through the TPU tunnel at 7B-layer size)
        self.quant_weight = Parameter(jnp.asarray(q._value), trainable=False)
        self.weight_scale = Parameter(jnp.asarray(s._value), trainable=False)
        self.bias = linear.bias
        self.weight_dtype = weight_dtype
        self.in_features = int(linear.weight.shape[0])
        self.out_features = int(linear.weight.shape[1])

    def forward(self, x):
        return weight_only_linear(x, self.quant_weight, self.bias,
                                  self.weight_scale, self.weight_dtype)


def quantize_linears_for_inference(layer, weight_dtype="int8",
                                   skip=lambda name, lin: False):
    """Swap every ``nn.Linear`` in the tree (in place) for
    :class:`WeightOnlyLinear` deploy storage. ``skip(qualified_name,
    linear)`` exempts layers (e.g. tiny heads). Returns the layer and the
    number of swaps."""
    from ..layer import common as _common
    n = [0]

    def visit(l, prefix):
        for name, sub in list(l._sub_layers.items()):
            qual = f"{prefix}{name}"
            if isinstance(sub, _common.Linear) and not skip(qual, sub):
                l._sub_layers[name] = WeightOnlyLinear(
                    sub, weight_dtype=weight_dtype)
                n[0] += 1
            elif isinstance(sub, Layer):
                visit(sub, qual + ".")

    visit(layer, "")
    return layer, n[0]


class Stub(Layer):
    """Quantization insertion point (reference: nn/quant/stub.py Stub): a
    no-op layer the QAT pass replaces with the configured quanter."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer

    def forward(self, input):
        return input


__all__.append("Stub")
