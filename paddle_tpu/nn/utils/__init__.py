"""nn.utils (reference: python/paddle/nn/utils/) — clip_grad helpers, param vector
conversion, weight/spectral norm wrappers."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ... import ops


def parameters_to_vector(parameters, name=None):
    return ops.concat([ops.reshape(p, [-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        chunk = vec[offset:offset + n]
        p._value = chunk._value.reshape(tuple(p.shape)).astype(p._value.dtype)
        offset += n


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    total = jnp.sqrt(sum(jnp.sum(jnp.square(g._value)) for g in grads)) \
        if norm_type == 2.0 else \
        jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g._value), norm_type))
                      for g in grads), 1.0 / norm_type)
    clip_coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for g in grads:
        g._value = g._value * clip_coef
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._value = jnp.clip(p.grad._value, -clip_value, clip_value)


def weight_norm(layer, name="weight", dim=0):
    """Re-parameterize weight = g * v/||v||. Applied lazily via a forward-pre hook."""
    import numpy as np
    from ..layer_base import Parameter
    w = getattr(layer, name)
    axes = tuple(i for i in range(w.ndim) if i != (dim % w.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(w._value), axis=axes, keepdims=True))
    g = Parameter(norm)
    v = Parameter(w._value)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def hook(l, inputs):
        vv = getattr(l, name + "_v")
        gg = getattr(l, name + "_g")
        nrm = ops.sqrt(ops.sum(vv * vv, axis=list(axes), keepdim=True))
        object.__setattr__(l, "_wn_cache", gg * vv / nrm)
        l.__dict__[name] = l._wn_cache
        return None

    layer.register_forward_pre_hook(hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    from ..layer_base import Parameter
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    axes = tuple(i for i in range(v.ndim))
    w = g._value * v._value / jnp.sqrt(
        jnp.sum(jnp.square(v._value),
                axis=tuple(i for i in range(v.ndim) if g._value.shape[i] == 1),
                keepdims=True))
    del layer._parameters[name + "_v"]
    del layer._parameters[name + "_g"]
    layer.add_parameter(name, Parameter(w))
    layer.__dict__.pop(name, None)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=0):
    import jax
    from ...core import random as _random
    from ..layer_base import Parameter
    w = getattr(layer, name)
    wv = w._value
    d = dim % wv.ndim
    w2d = jnp.moveaxis(wv, d, 0).reshape(wv.shape[d], -1)
    u0 = jax.random.normal(_random.next_key(), (w2d.shape[0],), jnp.float32)
    layer.register_buffer(name + "_u", Tensor(u0 / jnp.linalg.norm(u0)), persistable=True)
    orig = Parameter(wv)
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]

    def hook(l, inputs):
        wv_ = getattr(l, name + "_orig")._value
        u = l._buffers[name + "_u"]._value
        mat = jnp.moveaxis(wv_, d, 0).reshape(wv_.shape[d], -1)
        for _ in range(n_power_iterations):
            v = mat.T @ u
            v = v / jnp.maximum(jnp.linalg.norm(v), eps)
            u = mat @ v
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        sigma = u @ mat @ v
        l._buffers[name + "_u"]._value = u
        l.__dict__[name] = Tensor(wv_ / sigma, stop_gradient=False)
        return None

    layer.register_forward_pre_hook(hook)
    return layer
