"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference: python/paddle/nn/decode.py — a port of the tf.contrib.seq2seq
decoder contract: Decoder.initialize/step/finalize driven by a host loop.
Eager host loop here (decode lengths are data-dependent); each step's compute
is jitted op dispatch; the backtrace is nn.functional.gather_tree.
"""
from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .functional.extension import gather_tree
from . import functional as F


class Decoder:
    """Abstract decode contract (reference: nn/decode.py Decoder)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over an RNN cell (reference: nn/decode.py
    BeamSearchDecoder): states tiled to batch*beam, per-step top-k over
    beam*vocab, finished beams frozen onto end_token."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)

    # -- beam/batch reshaping helpers (reference names preserved) ------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        def tile(t):
            v = t._value if isinstance(t, Tensor) else jnp.asarray(t)
            v = jnp.repeat(v[:, None], beam_size, axis=1)
            return Tensor(v.reshape((-1,) + v.shape[2:]))
        return jax.tree_util.tree_map(
            tile, x, is_leaf=lambda t: isinstance(t, Tensor))

    def _merge_batch_beams(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(v.reshape((-1,) + v.shape[2:]))

    def _split_batch_beams(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(v.reshape((-1, self.beam_size) + v.shape[1:]))

    def _expand_to_beam_size(self, x):
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        return Tensor(jnp.repeat(v[:, None], self.beam_size, axis=1))

    def _tree(self, fn, tree):
        return jax.tree_util.tree_map(
            fn, tree, is_leaf=lambda t: isinstance(t, Tensor))

    def initialize(self, initial_cell_states):
        states = self._tree(self._expand_to_beam_size, initial_cell_states)
        sample = jax.tree_util.tree_leaves(states)[0]
        batch = sample.shape[0] if isinstance(sample, Tensor) else \
            sample._value.shape[0]
        self.batch_size = batch
        # beam 0 live, others -inf so the first top-k picks distinct tokens
        log_probs = jnp.tile(
            jnp.asarray([0.0] + [-1e9] * (self.beam_size - 1), jnp.float32),
            (batch, 1))
        init_ids = jnp.full((batch, self.beam_size), self.start_token,
                            jnp.int64)
        init_inputs = Tensor(init_ids)
        if self.embedding_fn is not None:
            init_inputs = self.embedding_fn(init_inputs)
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int64)
        state = self.StateWrapper(states, Tensor(log_probs), Tensor(finished),
                                  Tensor(lengths))
        return init_inputs, state, Tensor(finished)

    def _beam_search_step(self, logits, beam_state):
        batch, beam = self.batch_size, self.beam_size
        vocab = logits.shape[-1]
        step_lp = jax.nn.log_softmax(
            jnp.asarray(logits._value, jnp.float32), axis=-1)
        step_lp = step_lp.reshape(batch, beam, vocab)
        finished = beam_state.finished._value
        # finished beams emit only end_token with log-prob 0
        noend = jnp.full((vocab,), -1e9, jnp.float32).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[:, :, None], noend[None, None, :], step_lp)
        total = beam_state.log_probs._value[:, :, None] + step_lp
        flat = total.reshape(batch, beam * vocab)
        topv, topi = jax.lax.top_k(flat, beam)
        parent = (topi // vocab).astype(jnp.int64)
        token = (topi % vocab).astype(jnp.int64)
        prev_fin = jnp.take_along_axis(finished, parent, axis=1)
        next_fin = prev_fin | (token == self.end_token)
        prev_len = jnp.take_along_axis(beam_state.lengths._value, parent, axis=1)
        next_len = prev_len + (~prev_fin).astype(jnp.int64)

        def gather_state(t):
            # cell states arrive merged (batch*beam, ...) from the cell call;
            # store them split (batch, beam, ...) so the next step's merge works
            v = t._value if isinstance(t, Tensor) else jnp.asarray(t)
            v = v.reshape((batch, beam) + v.shape[1:])
            g = jnp.take_along_axis(
                v, parent.reshape((batch, beam) + (1,) * (v.ndim - 2)), axis=1)
            return Tensor(g)

        next_cell = self._tree(gather_state, beam_state.cell_states)
        next_state = self.StateWrapper(next_cell, Tensor(topv),
                                       Tensor(next_fin), Tensor(next_len))
        output = self.OutputWrapper(Tensor(topv), Tensor(token), Tensor(parent))
        return output, next_state, Tensor(next_fin)

    def step(self, time, inputs, states, **kwargs):
        merged_inputs = self._tree(self._merge_batch_beams, inputs)
        merged_states = self._tree(self._merge_batch_beams, states.cell_states)
        cell_out, next_cell = self.cell(merged_inputs, merged_states, **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        split_out = self._split_batch_beams(cell_out)
        beam_state = self.StateWrapper(next_cell, states.log_probs,
                                       states.finished, states.lengths)
        output, next_state, finished = self._beam_search_step(
            split_out, beam_state)
        next_inputs = Tensor(output.predicted_ids._value)
        if self.embedding_fn is not None:
            next_inputs = self.embedding_fn(next_inputs)
        return output, next_state, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        # outputs fields stacked (T, batch, beam) — backtrace parent pointers
        predicted = gather_tree(outputs.predicted_ids, outputs.parent_ids)
        return self.OutputWrapper(outputs.scores, predicted,
                                  outputs.parent_ids), final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive a Decoder until every sequence finishes (reference: nn/decode.py
    dynamic_decode)."""
    inputs, states, finished = decoder.initialize(inits)
    step_outputs = []
    time = 0
    max_steps = max_step_num if max_step_num is not None else 10 ** 9
    seq_len = None
    while time < max_steps:
        outputs, next_states, inputs, finished = decoder.step(
            time, inputs, states, **kwargs)
        if seq_len is None:
            seq_len = getattr(next_states, "lengths", None)
        fin = np.asarray(finished._value)
        step_outputs.append(outputs)
        states = next_states
        time += 1
        if fin.all():
            break

    def stack(field):
        vals = [getattr(o, field)._value for o in step_outputs]
        return Tensor(jnp.stack(vals, axis=0))

    if hasattr(step_outputs[0], "_fields"):
        stacked = type(step_outputs[0])(
            *[stack(f) for f in step_outputs[0]._fields])
    else:
        stacked = Tensor(jnp.stack([o._value for o in step_outputs], axis=0))
    lengths = getattr(states, "lengths", seq_len)
    final_outputs, final_states = decoder.finalize(stacked, states, lengths)
    if not output_time_major:
        def to_batch_major(t):
            v = t._value
            return Tensor(jnp.moveaxis(v, 0, 1))
        if hasattr(final_outputs, "_fields"):
            final_outputs = type(final_outputs)(
                *[to_batch_major(getattr(final_outputs, f))
                  for f in final_outputs._fields])
        else:
            final_outputs = to_batch_major(final_outputs)
    if return_length:
        return final_outputs, final_states, lengths
    return final_outputs, final_states
