"""Layer base class + Parameter (paddle.nn.Layer analog).

Reference: python/paddle/nn/layer/layers.py:353 — parameters/sublayers/buffers
registries, hooks, state_dict. Design deviation from the reference: a Layer here is a
*thin stateful shell* over pure-functional compute — its parameters can be temporarily
rebound to traced values (jit/functional_call.py), which is how one Layer definition
serves both the eager tape and the compiled pjit path.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator

import numpy as np
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.tensor import Tensor

_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None

#: nesting depth of active LazyGuard scopes (reference:
#: python/paddle/base/core LazyGuard / lazy_init) — under a guard,
#: create_parameter produces ABSTRACT values (jax.ShapeDtypeStruct) and
#: records the initializer for later materialization. An abstract model
#: costs no memory: the basis for AOT memory/sharding planning at scales
#: that cannot materialize on one host (tests/test_7b_scale.py).
#: Thread-local (like core.tensor's mode state): a guard on one thread must
#: not leak abstract params into layers built concurrently on another.
import threading as _threading


class _LazyState(_threading.local):
    def __init__(self):
        self.depth = 0


_LAZY_INIT = _LazyState()


def lazy_init_active() -> bool:
    return _LAZY_INIT.depth > 0


class Parameter(Tensor):
    """Trainable tensor (stop_gradient=False by default, optimizer-visible)."""

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.persistable = True

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def initialize(self):
        """Materialize a LazyGuard-created parameter by running its recorded
        initializer. No-op for already-materialized parameters. Honors dtype
        rewrites applied while abstract (e.g. ``layer.bfloat16()``) and any
        sharding assigned to the abstract value (materializes placed)."""
        spec = self.__dict__.pop("_lazy_init", None)
        if spec is not None:
            init, shape, _ = spec
            sharding = getattr(self._value, "sharding", None)
            value = init(shape, str(np.dtype(self._value.dtype)))
            value = value._value if isinstance(value, Tensor) else value
            if sharding is not None:
                import jax
                value = jax.device_put(value, sharding)
            self._value = value
        return self

    def __repr__(self):
        return "Parameter " + super().__repr__()


class ParamAttr:
    """paddle.ParamAttr — per-parameter config bundle."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if attr is False:
            return None
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # bare initializer
        return ParamAttr(initializer=attr)


class HookRemoveHelper:
    def __init__(self, container, key):
        self._container, self._key = container, key

    def remove(self):
        self._container.pop(self._key, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        d = object.__setattr__
        d(self, "_parameters", OrderedDict())
        d(self, "_sub_layers", OrderedDict())
        d(self, "_buffers", OrderedDict())
        d(self, "_non_persistable_buffer_names", set())
        d(self, "training", True)
        d(self, "_dtype", dtypes.convert_dtype(dtype) if dtype else dtypes.float32)
        d(self, "_forward_pre_hooks", OrderedDict())
        d(self, "_forward_post_hooks", OrderedDict())
        d(self, "_hook_id", 0)
        d(self, "_name_scope", name_scope or type(self).__name__.lower())

    # -- attribute routing ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if params is not None:
            for d in (self._parameters, self._sub_layers, self._buffers):
                d.pop(name, None)
            if isinstance(value, Parameter):
                self.__dict__.pop(name, None)  # drop any shadowing plain attr
                params[name] = value
                return
            if isinstance(value, Layer):
                self.__dict__.pop(name, None)
                self._sub_layers[name] = value
                return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for dname in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(dname)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} has no attribute {name!r}")

    def __delattr__(self, name):
        for d in (self._parameters, self._sub_layers, self._buffers):
            if name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # -- construction helpers ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """LayerHelper analog (reference: python/paddle/base/layer_helper.py:39)."""
        from .initializer import Constant, XavierNormal, Uniform
        attr = ParamAttr._to_attr(attr)
        if attr is None:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype is not None else self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            if is_bias:
                init = _GLOBAL_BIAS_INIT or Constant(0.0)
            else:
                init = _GLOBAL_WEIGHT_INIT or XavierNormal()
        if _LAZY_INIT.depth:
            import jax
            value = jax.ShapeDtypeStruct(tuple(int(s) for s in shape),
                                         jnp.dtype(dtype))
            p = Parameter(value, trainable=attr.trainable, name=attr.name)
            p._lazy_init = (init, [int(s) for s in shape], dtype)
            return p
        value = init(shape, dtype)
        p = Parameter(value, trainable=attr.trainable, name=attr.name)
        return p

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(tensor))
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            tensor.persistable = True
        return tensor

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        self._parameters[str(name)] = parameter
        return parameter

    # -- iteration -----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else f"{prefix}.{name}"), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix, True):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters("", include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix, True)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers("", include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield sub_prefix, layer
            yield from layer.named_sublayers(sub_prefix, False)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers("", include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- mode ---------------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            object.__setattr__(l, "training", True)
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            object.__setattr__(l, "training", False)
        return self

    # -- state dict ----------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(structured_name_prefix.rstrip("."),
                                             include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(structured_name_prefix.rstrip("."),
                                          include_sublayers):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            dest[name] = b
        # drop non-persistable buffers
        for lname, layer in self.named_sublayers("", include_self=True):
            for bname in layer._non_persistable_buffer_names:
                full = f"{lname}.{bname}" if lname else bname
                dest.pop(full, None)
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value._value if isinstance(value, Tensor) else jnp.asarray(value)
            if tuple(v.shape) != tuple(target.shape):
                raise ValueError(
                    f"shape mismatch for {name}: got {tuple(v.shape)}, "
                    f"expected {tuple(target.shape)}")
            # explicit copy: the source may belong to another live model whose
            # buffers get donated by a compiled train step
            target._value = jnp.array(v, dtype=target._value.dtype, copy=True)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # -- dtype / cast ---------------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_to(dtypes.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_to(dtypes.convert_dtype(dtype))
        return self

    def _cast_to(self, d):
        import jax
        for l in self.sublayers(include_self=True):
            object.__setattr__(l, "_dtype", d)
        for p in self.parameters():
            if jnp.issubdtype(p._value.dtype, jnp.floating):
                if isinstance(p._value, jax.ShapeDtypeStruct):
                    # abstract (LazyGuard) param: rewrite the aval dtype;
                    # initialize() materializes at the rewritten dtype
                    p._value = jax.ShapeDtypeStruct(
                        p._value.shape, jnp.dtype(d),
                        sharding=p._value.sharding)
                else:
                    p._value = p._value.astype(d)
        for b in self.buffers():
            if b is not None and jnp.issubdtype(b._value.dtype, jnp.floating):
                b._value = b._value.astype(d)

    def materialize(self):
        """Run the recorded initializers of every LazyGuard-created (abstract)
        parameter in this layer tree. Returns self."""
        for p in self.parameters():
            if hasattr(p, "initialize"):
                p.initialize()
        return self

    def float(self):
        return self.astype(dtypes.float32)

    def bfloat16(self):
        return self.astype(dtypes.bfloat16)

    def half(self):
        return self.astype(dtypes.float16)

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # -- call ----------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        lines = []
        extra = self.extra_repr()
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"({name}): {mod_str.strip()}")
        main = type(self).__name__
        if extra and not lines:
            return f"{main}({extra})"
        if not lines:
            return f"{main}()"
        body = "\n".join("  " + l for l in lines)
        return f"{main}(\n{body}\n)"
