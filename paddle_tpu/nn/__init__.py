"""paddle.nn analog."""
from .layer_base import Layer, Parameter, ParamAttr  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer.common import (  # noqa: F401
    Identity, Linear, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Embedding, Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, PixelShuffle,
    PixelUnshuffle, ChannelShuffle, Bilinear, Pad1D, Pad2D, Pad3D, ZeroPad1D,
    ZeroPad2D, ZeroPad3D, CosineSimilarity, Unfold, Fold, PairwiseDistance,
    Unflatten, FeatureAlphaDropout,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv1DTranspose, Conv2DTranspose, Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, MaxPool3D, AvgPool1D, AvgPool2D, AvgPool3D,
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    AdaptiveMaxPool2D, AdaptiveMaxPool3D, LPPool1D, LPPool2D,
    FractionalMaxPool2D, FractionalMaxPool3D, MaxUnPool1D, MaxUnPool2D,
    MaxUnPool3D,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, Silu, Swish, Mish, GELU, ELU, CELU, SELU, LeakyReLU,
    Hardtanh, Hardshrink, Softshrink, Hardsigmoid, Hardswish, Softplus, Softsign,
    Tanhshrink, ThresholdedReLU, LogSigmoid, Softmax, LogSoftmax, GLU, Maxout, PReLU,
    RReLU, Softmax2D,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, HuberLoss, KLDivLoss, MarginRankingLoss, CTCLoss,
    CosineEmbeddingLoss, TripletMarginLoss, HingeEmbeddingLoss, SoftMarginLoss,
    MultiLabelSoftMarginLoss, PoissonNLLLoss, GaussianNLLLoss,
    TripletMarginWithDistanceLoss, HSigmoidLoss, MultiMarginLoss, RNNTLoss,
    AdaptiveLogSoftmaxWithLoss,
)
from .layer.containers import (  # noqa: F401
    Sequential, LayerList, LayerDict, ParameterList, ParameterDict,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN, LSTM, GRU, RNNCellBase,
)

from .decode import BeamSearchDecoder, dynamic_decode, Decoder  # noqa: F401
# gradient-clip strategies live with the optimizers; paddle exposes them on nn too
from ..optimizer.clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)

from . import utils  # noqa: F401
