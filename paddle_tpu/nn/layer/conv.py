"""Conv layers (reference: python/paddle/nn/layer/conv.py). Weight layout
[out_c, in_c/groups, *k] matches the reference so state_dicts interchange."""
from __future__ import annotations

import math

import numpy as np

from ..layer_base import Layer
from ..initializer import Uniform, Constant
from .. import functional as F


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format=None, transpose=False, output_padding=0):
        super().__init__()
        k = (kernel_size,) * n if isinstance(kernel_size, int) else tuple(kernel_size)
        self._in_channels, self._out_channels = in_channels, out_channels
        self._kernel_size, self._stride, self._padding = k, stride, padding
        self._dilation, self._groups = dilation, groups
        self._data_format = data_format
        self._padding_mode = padding_mode
        self._output_padding = output_padding
        self._transpose = transpose
        fan_in = in_channels * int(np.prod(k)) // groups
        bound = 1.0 / math.sqrt(fan_in)
        if transpose:
            wshape = (in_channels, out_channels // groups) + k
        else:
            wshape = (out_channels, in_channels // groups) + k
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr,
                         data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)
