"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from ..layer_base import Layer
from ..initializer import Constant
from .. import functional as F


def _simple(name, fn_name=None, **fixed):
    fn = getattr(F, fn_name or name.lower())

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            self._args, self._kwargs = args, {**fixed, **kwargs}

        def forward(self, x):
            return fn(x, *self._args, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _simple("ReLU")
ReLU6 = _simple("ReLU6")
Sigmoid = _simple("Sigmoid")
Tanh = _simple("Tanh")
Silu = _simple("Silu")
Swish = _simple("Swish")
Mish = _simple("Mish")
GELU = _simple("GELU")
ELU = _simple("ELU")
CELU = _simple("CELU")
SELU = _simple("SELU")
LeakyReLU = _simple("LeakyReLU", "leaky_relu")
Hardtanh = _simple("Hardtanh")
Hardshrink = _simple("Hardshrink")
Softshrink = _simple("Softshrink")
Hardsigmoid = _simple("Hardsigmoid")
Hardswish = _simple("Hardswish")
Softplus = _simple("Softplus")
Softsign = _simple("Softsign")
Tanhshrink = _simple("Tanhshrink")
ThresholdedReLU = _simple("ThresholdedReLU", "thresholded_relu")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
Softmax = _simple("Softmax")
LogSoftmax = _simple("LogSoftmax", "log_softmax")
GLU = _simple("GLU")
Maxout = _simple("Maxout")
RReLU = _simple("RReLU", "rrelu")


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW inputs (reference:
    nn/layer/activation.py Softmax2D — softmax at axis=-3)."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        assert x.ndim in (3, 4), \
            f"Softmax2D requires a 3D or 4D tensor as input. Received: {x.ndim}D."
        return F.softmax(x, axis=-3)
